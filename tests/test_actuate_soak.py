"""Closed-loop actuation soak (ISSUE 14 acceptance): the PR 13 chaos
soak grown actuated. A live monitor scrapes a real ServingEngine with a
small bounded queue, driven by the seeded multi-tenant mix; the
injected serving-path fault (scheduler stall + chaos ``slow`` on the
serving collector) overflows the queue, rejections inflate the chat
tenant's error rate, and the fast-window SLO burn alert pages. With
actuation live, the page itself triggers the remedy: a global shed
(whose shed completions are NEVER errors — the satellite accounting
fix, without which the remedy would latch the very SLO that fired it)
plus a capacity nudge, rejections stop while the fault is STILL
active, and the alert clears measurably faster than the same fault
un-actuated. Both arms run in one test against the same warm engine:
episode A holds the engine untouched behind ``dry_run`` (the journaled
intent IS the PR 13 no-actuation baseline), episode B acts for real.
Asserted through the public surfaces: ``/api/slo``, ``/api/actuate``,
and the journal's seq order — slo fired < actuate fired < slo resolved
< actuate reverted. No unit seams anywhere in the chain:
Request.tenant → engine tenant gauges → serving collector →
``serving.chat.error_rate`` TSDB series → compiled burn expressions →
SLO page-state series → actuation policies → EngineActuator →
ServingEngine."""

import asyncio
import json
import time

from tests.test_server_api import get_json
from tpumon.actuate import EngineActuator
from tpumon.app import build
from tpumon.collectors.chaos import ChaosCollector, Fault
from tpumon.config import load_config
from tpumon.loadgen.serving import ServingEngine, start_metrics_server
from tpumon.loadgen.traffic import TenantSpec, TrafficSim

# Tick / fault geometry. The engine queue is bounded at 8; the 0.25 s
# per-step stall caps completion throughput at ~3 req/s against a
# ~11.5 rps offered load, so the queue overflows within ~1 s of the
# fault and rejections inflate the windowed per-tenant error rate. The
# serving scrape interval EQUALS the tick so every error-rate window
# spans ~2 stalled pump iterations — a shorter window would alias
# against the stall-paced submission bursts and flap the bad-event
# series (a window between bursts sees zero rejections). The shed
# policy drops 0.8 of ALL admissions, taking offered load well below
# the degraded capacity: rejections cease while the stall is still
# active — recovery no longer waits for the fault to lift.
SAMPLE_INTERVAL_S = 0.5
SERVING_INTERVAL_S = 0.5
DEGRADE_STALL_S = 0.25
MAX_QUEUE = 8
ERROR_RATE_MAX = 0.05
# Ticks the fault is held PAST the page before lifting, identical in
# both episodes: the un-actuated arm structurally cannot clear earlier
# (rejections flow until the lift), the actuated arm can.
HOLD_TICKS = 6

SLOS = [{
    "name": "chat_errors",
    "tenant": "chat",
    "expr": f'serving.error_rate{{tenant="chat"}} > {ERROR_RATE_MAX:g}',
    "target": 0.99,
    "window": "1h",
    # Second-scale burn windows so fault -> page -> un-page fits in a
    # test; thresholds stay the production 14.4x / 6x.
    "fast": ["1s", "3s"],
    "slow": ["2s", "6s"],
}]

# Both policies key off the SLO engine's recorded page-state series
# (docs/actuation.md): the shed on the page alone, the capacity nudge
# only while the queue trend corroborates (a recording-rule window,
# never a point walk) — so both actions journal seq-AFTER the page.
# `and` intersects vectors BY LABELS (docs/query.md): the paging side
# must collapse to the no-label vector `sum()` yields before it can
# meet the label-less queue_depth series. The trend window is 6s > 2,
# deliberately loose: the PAGING gate is what guards against spurious
# fires (healthy queue avg is well under 2 and paging is 0 anyway);
# the trend's job is corroboration-through-a-recording-rule. A tight
# bar (2s > 6, then 4s > 4) flaked under full-suite load: in the
# ACTUATED episode the shed collapses the queue within a tick or two
# of the page, and at the page instant the pegged-at-8 ticks are only
# ~a third of a short window (avg ≈ 3.8 < 4) — one chaos-slowed
# scrape lagging queue_depth closed the window before the nudge
# fired. The 6s window stays > 2 from page time until well after the
# shed drains the queue, in both episodes.
PAGE = 'slo.paging{slo="chat_errors"} > 0'
ACTUATIONS = [
    {"name": "shed_load", "when": PAGE, "action": "shed",
     "tenant": "*", "fraction": 0.8, "cooldown_s": 0,
     "fire_hold": 1, "clear_hold": 4},
    {"name": "grow_budget",
     "when": ('sum(slo.paging{slo="chat_errors"}) > 0'
              " and avg_over_time(queue_depth[6s]) > 2"),
     "action": "capacity", "prefill_budget": 4, "cooldown_s": 0,
     "fire_hold": 1, "clear_hold": 4},
]


async def wait_until(fn, what: str, timeout_s: float = 30.0):
    """Poll ``fn`` until truthy off the event-loop thread (a blocking
    HTTP call on the loop would deadlock against the server)."""
    t0 = time.monotonic()
    while True:
        v = await asyncio.to_thread(fn)
        if v:
            return v
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError(f"actuate soak: timed out waiting for {what}")
        await asyncio.sleep(0.05)


def test_actuated_recovery_beats_unactuated_baseline():
    engine = ServingEngine(max_queue=MAX_QUEUE)
    # Short recency window so recovery is visible within the budgets.
    engine.tenant_window_s = 2.0
    metrics_server, port = start_metrics_server(engine)
    sim = TrafficSim(engine, [
        TenantSpec(name="chat", scenario="chat", rps=10.0, max_new=4),
        TenantSpec(name="rag", scenario="rag", rps=1.0,
                   prompt_chunks=3, max_new=4),
        TenantSpec(name="batch", scenario="batch", rps=0.5, max_new=8),
    ], seed=42)

    cfg = load_config(env={
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_K8S_MODE": "none",
        "TPUMON_COLLECTORS": "host,accel,serving",
        "TPUMON_SERVING_TARGETS": f"http://127.0.0.1:{port}/metrics",
        "TPUMON_SAMPLE_INTERVAL_S": str(SAMPLE_INTERVAL_S),
        "TPUMON_SERVING_INTERVAL_S": str(SERVING_INTERVAL_S),
        "TPUMON_ANOMALY_DETECT": "0",
        "TPUMON_SLOS": json.dumps(SLOS),
        "TPUMON_ACTUATIONS": json.dumps(ACTUATIONS),
        # The policy asks for 0.8; the config clamp must not bite it
        # (the clamp's own math is unit-tested).
        "TPUMON_SHED_MAX_FRACTION": "0.85",
        "TPUMON_CHAOS": "slow:serving:0",
        "TPUMON_CHAOS_SEED": "42",
    })
    sampler, server = build(cfg)
    assert isinstance(sampler.serving, ChaosCollector)
    assert sampler.slo is not None
    assert sampler.actuate is not None
    # Bind the in-process engine behind the narrow actuator interface
    # (app.run does exactly this for --serve-loadgen).
    sampler.actuate.bind_engine(engine)
    assert isinstance(sampler.actuate.actuator, EngineActuator)

    async def scenario():
        sim.start()
        # Warm outside the judged window: first prefill/decode jits
        # take seconds; backlogged compile-era requests carry their
        # queue wait as multi-second TTFTs and the overflowed queue as
        # rejections. Wait for flow, drain, then age the window out.
        await wait_until(
            lambda: engine.tenants.get("chat")
            and engine.tenants["chat"].completed >= 3,
            "chat traffic flowing", timeout_s=60.0)
        await wait_until(
            lambda: len(engine._queue) == 0,
            "compile-era queue backlog to drain", timeout_s=60.0)
        await asyncio.sleep(engine.tenant_window_s + 0.5)

        await sampler.start()
        await server.start()
        mport = server.port

        def slo_row():
            return get_json(mport, "/api/slo")["slos"][0]

        def fast_firing():
            return slo_row()["burn"]["fast"]["firing"]

        def ticks():
            return sampler.watchdogs["fast"].ticks

        def events(kind):
            return get_json(mport, f"/api/events?kind={kind}")["events"]

        def policy_rows():
            return {r["name"]: r
                    for r in get_json(mport, "/api/actuate")["policies"]}

        await wait_until(
            lambda: "serving.chat.error_rate" in sampler.history.series,
            "per-tenant serving series")

        async def episode(label):
            """Inject the fault, hold it HOLD_TICKS past the page, lift
            it; return (page seq floor, ticks from page to un-page)."""
            await wait_until(
                lambda: slo_row()["burn"]["fast"]["long"] == 0.0,
                f"{label}: clean baseline", timeout_s=60.0)
            assert not await asyncio.to_thread(fast_firing)
            seq0 = max(
                (e["seq"] for e in await asyncio.to_thread(
                    lambda: events("slo") + events("actuate"))),
                default=0)
            sampler.serving.set_faults([Fault(mode="slow", param=150.0)])
            sim.degrade(DEGRADE_STALL_S)
            t_fault = ticks()
            await wait_until(fast_firing, f"{label}: fast-window page",
                             timeout_s=30.0)
            t_page = ticks()
            assert t_page - t_fault <= 10, (
                f"{label}: page took {t_page - t_fault} ticks (budget 10)")
            await wait_until(lambda: ticks() - t_page >= HOLD_TICKS,
                             f"{label}: fault hold", timeout_s=30.0)
            sim.degrade(0)
            sampler.serving.set_faults([])
            await wait_until(lambda: not fast_firing(),
                             f"{label}: page to clear", timeout_s=30.0)
            recovery = ticks() - t_page
            # Episode teardown: every policy back to idle (reverts
            # journaled), so the next episode starts from scratch.
            await wait_until(
                lambda: all(r["state"] == "idle"
                            for r in policy_rows().values()),
                f"{label}: policies idle", timeout_s=30.0)
            return seq0, recovery

        # --- episode A: the un-actuated baseline (dry-run) ----------
        sampler.actuate.dry_run = True
        seq_a, recovery_baseline = await episode("baseline")
        # Intent was journaled (the policy DID fire, dry)...
        a_fired = [e for e in await asyncio.to_thread(events, "actuate")
                   if e["seq"] > seq_a and e.get("state") == "fired"]
        assert any(e["policy"] == "shed_load" for e in a_fired)
        assert all(e.get("dry_run") for e in a_fired)
        # ...but provably nothing reached the engine.
        assert engine.shed_total == 0
        assert engine.shed_fractions() == {}
        assert engine.cfg.prefill_chunk_budget == 1
        assert engine.requeued_total == 0

        # --- episode B: the loop closed for real ---------------------
        sampler.actuate.dry_run = False
        seq_b, recovery_actuated = await episode("actuated")

        # The headline: measurably faster recovery, zero human steps.
        assert recovery_actuated < recovery_baseline, (
            f"actuated recovery ({recovery_actuated} ticks) not faster "
            f"than un-actuated baseline ({recovery_baseline} ticks)")
        # The un-actuated arm cannot clear before the lift at
        # page+HOLD_TICKS; the actuated arm recovers DURING the fault.
        assert recovery_baseline > HOLD_TICKS
        assert recovery_actuated <= 20 and recovery_baseline <= 20

        # The remedy actually ran: admissions were shed (as their own
        # terminal status — never errors), capacity was nudged and both
        # were reverted on recovery.
        assert engine.shed_total > 0
        assert engine.shed_fractions() == {}          # reverted
        assert engine.cfg.prefill_chunk_budget == 1   # baseline restored
        rows = await asyncio.to_thread(policy_rows)
        assert rows["shed_load"]["fired"] >= 1
        assert rows["shed_load"]["reverted"] >= 1
        assert rows["grow_budget"]["fired"] >= 1
        assert not rows["shed_load"]["dry_run"]

        # Journal seq order tells the closed-loop story end to end:
        # slo fired < both actuations fired < slo resolved < shed
        # reverted — observation, remedy, recovery, revert.
        slo_ev = [e for e in await asyncio.to_thread(events, "slo")
                  if e["seq"] > seq_b and e.get("window") == "fast"]
        act_ev = [e for e in await asyncio.to_thread(events, "actuate")
                  if e["seq"] > seq_b]
        page_seq = next(e["seq"] for e in slo_ev if e["state"] == "fired")
        resolved_seq = next(
            e["seq"] for e in slo_ev if e["state"] == "resolved")
        shed_seq = next(e["seq"] for e in act_ev
                        if e["policy"] == "shed_load"
                        and e["state"] == "fired")
        grow_seq = next(e["seq"] for e in act_ev
                        if e["policy"] == "grow_budget"
                        and e["state"] == "fired")
        revert_seq = next(e["seq"] for e in act_ev
                          if e["policy"] == "shed_load"
                          and e["state"] == "reverted")
        assert page_seq < shed_seq < resolved_seq < revert_seq
        assert page_seq < grow_seq
        # None of episode B's performed actions were dry.
        fired_b = [e for e in act_ev if e["state"] == "fired"]
        assert fired_b and all(not e.get("dry_run") for e in fired_b)
        # The fired events carry the audit trail: the triggering
        # expression and the action detail.
        shed_fired = next(e for e in fired_b if e["policy"] == "shed_load")
        assert shed_fired["expr"] == PAGE
        assert "shed tenant *" in shed_fired["msg"]
        # Chaos-slowed scrapes still landed throughout (the monitor
        # kept seeing while it acted).
        assert sampler.latest["serving"].ok

        await server.stop()
        await sampler.stop()

    try:
        asyncio.run(scenario())
    finally:
        sim.stop()
        metrics_server.shutdown()
        metrics_server.server_close()
