"""Pallas kernel tests (interpret mode on the CPU test mesh; the same
kernels compile on real TPUs — verified on v5e where the tiled matmul
outruns XLA's dot for the burn shapes)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpumon.ops.matmul import matmul  # noqa: E402


def _ref(a, b):
    return (a.astype(jnp.float32) @ b.astype(jnp.float32)).astype(jnp.bfloat16)


@pytest.mark.parametrize(
    "m,k,n,bm,bk,bn",
    [
        (128, 64, 128, 128, 64, 128),  # single tile
        (256, 128, 256, 128, 64, 128),  # multi-tile all axes
        (256, 256, 128, 128, 128, 128),  # k-major accumulation
    ],
)
def test_matmul_matches_reference(m, k, n, bm, bk, bn):
    a = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.bfloat16)
    out = matmul(a, b, block_m=bm, block_n=bn, block_k=bk, interpret=True)
    ref = _ref(a, b)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=6e-2, atol=6e-2,
    )


def test_matmul_rejects_nondivisible():
    a = jnp.zeros((100, 64), jnp.bfloat16)
    b = jnp.zeros((64, 128), jnp.bfloat16)
    with pytest.raises(AssertionError):
        matmul(a, b, block_m=128, block_n=128, block_k=64, interpret=True)


def test_burn_uses_pallas_flag():
    from tpumon.loadgen.burn import mxu_burn

    out = mxu_burn(seconds=0.2, size=128, iters=2, use_pallas=False)
    assert out["tflops"] > 0 and out["pallas"] is False


# ---------------- int8 weight-only matmul (tpumon.ops.quant_matmul) ----


def test_quantized_matmul_matches_dequant_reference():
    from tpumon.loadgen.quant import quantize
    from tpumon.ops.quant_matmul import quantized_matmul_pallas

    a = jax.random.normal(jax.random.PRNGKey(0), (256, 512), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (512, 512), jnp.float32)
    qt = quantize(w)
    out = quantized_matmul_pallas(
        a, qt.q, qt.scale, block_m=128, block_n=128, block_k=128,
        interpret=True,
    )
    ref = a @ qt.astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


def test_quantized_matmul_scale_applied_once_across_k_steps():
    # Two K steps with a non-trivial scale: wrong placement of the scale
    # (inside the K loop) would double-apply it.
    from tpumon.ops.quant_matmul import quantized_matmul_pallas

    a = jnp.ones((128, 256), jnp.float32)
    q = jnp.ones((256, 128), jnp.int8)
    scale = jnp.full((128,), 0.5, jnp.float32)
    out = quantized_matmul_pallas(
        a, q, scale, block_m=128, block_n=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), 256 * 0.5)


def test_quantized_matmul_fallback_for_decode_shapes():
    from tpumon.loadgen.quant import quantize
    from tpumon.ops.quant_matmul import quantized_matmul

    a = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 48), jnp.float32)
    qt = quantize(w)
    out = quantized_matmul(a, qt.q, qt.scale, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a @ qt.astype(jnp.float32)),
        rtol=1e-5, atol=1e-5,
    )


def test_int8_burn_runs_off_tpu():
    from tpumon.loadgen.burn import int8_burn

    out = int8_burn(seconds=0.2, size=128, iters=2, use_pallas=False)
    assert out["tflops"] > 0 and out["weight_gbps"] > 0
    assert out["pallas"] is False


def test_paged_burn_runs_off_tpu():
    from tpumon.loadgen.burn import paged_burn

    out = paged_burn(seconds=0.2, batch=2, n_heads=4, n_kv_heads=2,
                     head_dim=16, page_size=8, context=32,
                     use_pallas=False)
    assert out["decode_steps_per_sec"] > 0 and out["kv_gbps"] > 0
    assert out["pallas"] is False
