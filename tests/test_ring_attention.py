"""Ring attention correctness on the virtual CPU mesh: the sequence-
parallel implementation must match full-sequence attention exactly."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from tpumon.loadgen.ring_attention import (  # noqa: E402
    reference_attention,
    ring_attention,
)


def make_qkv(b=2, t=32, h=4, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("n_dev", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(n_dev, causal):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    q, k, v = make_qkv(t=32)
    ref = reference_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_ring_with_sharded_inputs():
    """Inputs already device-put with the sequence sharding (the real
    long-context layout) work identically."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = make_qkv(t=64)
    spec = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh)
    assert out.sharding.spec == P(None, "seq", None, None)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_bf16_tolerance():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = make_qkv(t=32, dtype=jnp.bfloat16)
    ref = reference_attention(q, k, v)
    out = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_first_row_fully_masked_is_finite():
    """Causal first token attends only itself; no NaNs from the running
    -inf max guards."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = make_qkv(t=16)
    out = ring_attention(q, k, v, mesh)
    assert bool(jnp.all(jnp.isfinite(out)))


# ----------------------------------------------------------------- zigzag


from tpumon.loadgen.ring_attention import (  # noqa: E402
    zigzag_indices,
    zigzag_inverse,
    zigzag_ring_attention,
)


def test_zigzag_permutation_roundtrip():
    t, n = 32, 4
    zi, inv = zigzag_indices(t, n), zigzag_inverse(t, n)
    x = jnp.arange(t)
    assert (x[zi][inv] == x).all()
    # Chip 0's shard holds half-blocks 0 and 2n-1.
    hb = t // (2 * n)
    shard0 = np.asarray(zi[: 2 * hb])
    assert list(shard0) == list(range(0, hb)) + list(range(t - hb, t))


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_zigzag_matches_reference(n_dev):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    q, k, v = make_qkv(t=32)
    t = q.shape[1]
    zi, inv = zigzag_indices(t, n_dev), zigzag_inverse(t, n_dev)
    out = zigzag_ring_attention(q[:, zi], k[:, zi], v[:, zi], mesh)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, inv]), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_zigzag_sharded_inputs_keep_layout():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = make_qkv(t=64)
    zi = zigzag_indices(64, 4)
    spec = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x[:, zi], spec) for x in (q, k, v))
    out = zigzag_ring_attention(qs, ks, vs, mesh)
    assert out.sharding.spec == P(None, "seq", None, None)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out)[:, np.asarray(zigzag_inverse(64, 4))],
        np.asarray(ref), rtol=1e-5, atol=1e-5,
    )


# --------------------------------------------------------------- gradients


@pytest.mark.parametrize("n_dev", [2, 4])
def test_ring_grads_match_reference(n_dev):
    """Training through ring attention is the point of sequence
    parallelism — the backward pass (through ppermute + the online
    softmax) must produce the same q/k/v grads as full attention."""
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    q, k, v = make_qkv(t=16)

    def loss_ring(q, k, v):
        out = ring_attention(q, k, v, mesh, axis="seq", causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n_dev", [2, 4])
def test_zigzag_grads_match_reference(n_dev):
    """Same for the zigzag schedule: grads through lax.cond-skipped
    blocks and the layout permutation must match full attention."""
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("seq",))
    q, k, v = make_qkv(t=16)
    t = q.shape[1]
    zi, inv = zigzag_indices(t, n_dev), zigzag_inverse(t, n_dev)

    def loss_zz(q, k, v):
        out = zigzag_ring_attention(q[:, zi], k[:, zi], v[:, zi], mesh)
        return jnp.sum(out[:, inv].astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v).astype(jnp.float32) ** 2)

    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_zz, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)
