"""Multi-host slice simulation (SURVEY §4.4): a v5p-64 fake slice, fault
injection by killing hosts, slice-failure alerting and exporter
aggregation — multi-node behavior without a cluster."""

import asyncio

from tpumon.alerts import AlertEngine
from tpumon.collectors.accel_fake import FakeTpuCollector
from tpumon.config import load_config
from tpumon.exporter import render_exporter
from tpumon.metrics_text import parse_metrics_text, samples_by_name
from tpumon.sampler import Sampler
from tpumon.topology import slice_views


def make_sampler(topology="v5p-64", expected=64):
    cfg = load_config(
        env={
            "TPUMON_ACCEL_BACKEND": f"fake:{topology}",
            "TPUMON_EXPECTED_SLICE_CHIPS": f'{{"slice-0": {expected}}}',
            "TPUMON_COLLECTORS": "accel",
        }
    )
    accel = FakeTpuCollector(topology=topology)
    return cfg, accel, Sampler(cfg, accel=accel)


def alert_keys(engine: AlertEngine):
    return {a["key"] for sev in engine.last.values() for a in sev}


def test_v5p64_healthy_slice():
    cfg, accel, sampler = make_sampler()
    asyncio.run(sampler.tick_fast())
    views = sampler.slices()
    assert len(views) == 1
    assert views[0].reporting_chips == 64
    assert views[0].missing_chips == 0
    assert len(views[0].hosts) == 16
    assert "slice.slice-0.missing" not in alert_keys(sampler.engine)


def test_host_failure_triggers_slice_alert():
    cfg, accel, sampler = make_sampler()
    asyncio.run(sampler.tick_fast())
    accel.kill_host("tpu-host-7")  # fault injection: one host of 16 dies
    asyncio.run(sampler.tick_fast())
    views = sampler.slices()
    assert views[0].reporting_chips == 60
    assert views[0].missing_chips == 4
    keys = alert_keys(sampler.engine)
    assert "slice.slice-0.missing" in keys
    crit = sampler.engine.last["critical"][0]
    assert "60/64" in crit["desc"]


def test_recovery_clears_slice_alert():
    cfg, accel, sampler = make_sampler()
    accel.kill_host("tpu-host-3")
    asyncio.run(sampler.tick_fast())
    assert "slice.slice-0.missing" in alert_keys(sampler.engine)
    accel.revive_host("tpu-host-3")
    asyncio.run(sampler.tick_fast())
    assert "slice.slice-0.missing" not in alert_keys(sampler.engine)


def test_exporter_aggregates_all_hosts():
    cfg, accel, sampler = make_sampler()
    asyncio.run(sampler.tick_fast())
    by = samples_by_name(parse_metrics_text(render_exporter(sampler)))
    duty = by["tpu_mxu_duty_cycle_pct"]
    assert len(duty) == 64
    hosts = {s.labels["host"] for s in duty}
    assert len(hosts) == 16
    assert by["tpu_slice_reporting_chips"][0].value == 64
    assert by["tpu_slice_expected_chips"][0].value == 64


def test_ici_rates_prune_dead_hosts():
    """Aggregate ICI traffic must drop when a host dies (code-review
    finding: stale rates were carried forever)."""
    cfg, accel, sampler = make_sampler(topology="v5p-8", expected=8)

    async def scenario():
        t = [1000.0]
        accel.clock = lambda: t[0]
        await sampler.tick_fast()
        t[0] += 10
        await sampler.tick_fast()
        assert len(sampler.ici_rates) == 8
        accel.kill_host("tpu-host-1")
        t[0] += 10
        await sampler.tick_fast()
        assert len(sampler.ici_rates) == 4
        assert not any("tpu-host-1" in cid for cid in sampler.ici_rates)

    asyncio.run(scenario())


def test_multi_slice_topology():
    """Two independent fake slices feeding one alert engine — the
    multi-slice aggregation path."""
    a = FakeTpuCollector(topology="v5e-8", slice_id="slice-a", host_prefix="ha")
    b = FakeTpuCollector(topology="v5p-8", slice_id="slice-b", host_prefix="hb")
    chips = a.chips() + b.chips()
    views = slice_views(chips, {"slice-a": 8, "slice-b": 8})
    assert [v.slice_id for v in views] == ["slice-a", "slice-b"]
    assert all(v.missing_chips == 0 for v in views)
    engine = AlertEngine()
    engine.evaluate(chips=chips, slices=views)
    assert "slice.slice-a.missing" not in alert_keys(engine)
