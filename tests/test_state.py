"""Checkpoint/resume of monitor state (tpumon.state, SURVEY §5.4).

The reference loses all state on restart (monitor_server.js:157); these
tests pin the upgrade: ring history, alert timeline and pod-transition
baseline round-trip through a StateStore snapshot, and a pod restart
*while the monitor was down* still alerts after resume.
"""

import json
import time

from tpumon.app import build
from tpumon.config import load_config
from tpumon.state import StateStore, restore_state, snapshot_state

ENV = {
    "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
    "TPUMON_K8S_MODE": "none",
    "TPUMON_COLLECTORS": "host,accel",
    "TPUMON_PORT": "0",
}


def make_sampler():
    sampler, _ = build(load_config(env=ENV))
    return sampler


def pods(status="Running", restarts=0):
    return [
        {"namespace": "ns", "name": "job-0", "status": status, "restarts": restarts}
    ]


def test_round_trip_history_and_alert_state():
    a = make_sampler()
    now = time.time()
    a.history.record("cpu", 42.0, ts=now - 60)
    a.history.record("cpu", 43.0, ts=now)
    a.history.record("chip.h0/chip-0.mxu", 71.5, ts=now)
    a.engine.evaluate(host={"cpu": {"percent": 96.0}}, pods=pods(restarts=1))

    b = make_sampler()
    assert restore_state(b, snapshot_state(a))
    assert b.history.snapshot_series("cpu", 30)["data"][-1] == 43.0
    assert b.history.snapshot_series("chip.h0/chip-0.mxu", 30)["data"] == [71.5]
    # Timeline survived; active keys survived so the same alert doesn't
    # re-append a duplicate "fired" event after resume.
    fired = [e for e in b.engine.events if e["state"] == "fired"]
    assert any(e["key"] == "host.cpu.critical" for e in fired)
    n_events = len(b.engine.events)
    b.engine.evaluate(host={"cpu": {"percent": 96.0}}, pods=pods(restarts=1))
    assert len(b.engine.events) == n_events


def test_pod_restart_during_downtime_alerts_after_resume():
    a = make_sampler()
    a.engine.evaluate(pods=pods(restarts=0))
    state = snapshot_state(a)

    b = make_sampler()
    assert restore_state(b, state)
    r = b.engine.evaluate(pods=pods(restarts=2))  # restarted while down
    assert any(x["key"] == "pod.ns/job-0.restarted" for x in r["serious"])


def test_restore_prunes_points_outside_window():
    a = make_sampler()
    now = time.time()
    a.history.record("cpu", 1.0, ts=now - a.history.window_s - 600)
    a.history.record("cpu", 2.0, ts=now)
    b = make_sampler()
    assert restore_state(b, snapshot_state(a))
    assert b.history.snapshot_series("cpu", 30)["data"] == [2.0]


def test_stale_or_malformed_snapshot_rejected():
    b = make_sampler()
    good = snapshot_state(make_sampler())
    assert not restore_state(b, {"version": 99})
    assert not restore_state(b, {**good, "saved_at": time.time() - 90000})
    assert not restore_state(b, {**good, "history": "nope"})


def test_statestore_file_round_trip_and_corruption(tmp_path):
    path = tmp_path / "state.json"
    store = StateStore(str(path))
    a = make_sampler()
    a.history.record("cpu", 7.0)
    assert store.save(a)
    assert store.last_save_ts is not None

    b = make_sampler()
    assert StateStore(str(path)).restore_into(b)
    assert b.history.snapshot_series("cpu", 30)["data"] == [7.0]

    path.write_text("{corrupt")
    c = make_sampler()
    assert not StateStore(str(path)).restore_into(c)  # degrades, no raise
    assert not StateStore(str(tmp_path / "missing.json")).restore_into(c)


def test_snapshot_is_json_serializable_end_to_end(tmp_path):
    a = make_sampler()
    a.engine.evaluate(
        host={"cpu": {"percent": 96.0}},
        pods=pods(status="Pending"),
        serving=[{"target": "t", "ok": False, "error": "down"}],
    )
    # The exact bytes the StateStore writes must round-trip through json.
    assert restore_state(make_sampler(), json.loads(json.dumps(snapshot_state(a))))


def test_config_state_keys():
    cfg = load_config(
        env={**ENV, "TPUMON_STATE_PATH": "/tmp/s.json", "TPUMON_STATE_INTERVAL_S": "5"}
    )
    assert cfg.state_path == "/tmp/s.json"
    assert cfg.state_interval_s == 5.0


def test_restore_coarse_seam_bucket_not_duplicated():
    # Regression: a fine point mid-bucket must evict the snapshot's
    # full-bucket coarse mean for that bucket (one entry per bucket, the
    # replayed one), not coexist with it at the same timestamp.
    import time as _time

    a = make_sampler()
    now = _time.time()
    step = a.history.coarse_step_s
    bucket = int((now - 600) // step)
    seam_ts = (bucket + 0.6) * step  # fine point lands mid-bucket
    for i in range(40):  # enough fine points to span several buckets
        a.history.record("cpu", 10.0, ts=seam_ts + i * 10)
    state = snapshot_state(a)

    b = make_sampler()
    assert restore_state(b, state)
    coarse_ts = [t for t, _ in b.history.series["cpu"].coarse]
    assert len(coarse_ts) == len(set(coarse_ts)), "duplicate seam bucket"
