"""Golden-input tests for the pod parser (SURVEY §4.1: kubectl -o json pod
dumps incl. containerStatuses edge cases, shape from monitor_server.js:99-112)."""

import asyncio

from tpumon.collectors.k8s import K8sCollector, humanize_age, parse_pod_list

NOW = 1_700_000_000.0


def pod_doc(
    name="p1",
    ns="default",
    phase="Running",
    restarts=(0,),
    start_offset_s=3600.0,
    **extra,
):
    statuses = [{"restartCount": r} for r in restarts]
    import datetime as dt

    start = dt.datetime.fromtimestamp(NOW - start_offset_s, dt.timezone.utc)
    doc = {
        "metadata": {"namespace": ns, "name": name, "labels": {}},
        "spec": {"nodeName": "node-1", "nodeSelector": {}},
        "status": {
            "phase": phase,
            "startTime": start.isoformat().replace("+00:00", "Z"),
            "containerStatuses": statuses,
        },
    }
    for k, v in extra.items():
        parts = k.split("__")
        d = doc
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return doc


def test_humanize_age_matches_reference_buckets():
    # days / hours / minutes (monitor_server.js:106-110)
    assert humanize_age(2 * 86400 + 5) == "2d"
    assert humanize_age(3 * 3600 + 100) == "3h"
    assert humanize_age(150) == "2m"
    assert humanize_age(10) == "0m"


def test_parse_basic_fields():
    pods = parse_pod_list({"items": [pod_doc(restarts=(2, 3))]}, now=NOW)
    assert len(pods) == 1
    p = pods[0]
    assert p["namespace"] == "default" and p["name"] == "p1"
    assert p["status"] == "Running"
    assert p["restarts"] == 5  # summed over containers (monitor_server.js:104)
    assert p["age"] == "1h"
    assert p["node"] == "node-1"


def test_parse_pending_without_container_statuses():
    doc = pod_doc(phase="Pending")
    del doc["status"]["containerStatuses"]
    del doc["status"]["startTime"]
    p = parse_pod_list({"items": [doc]}, now=NOW)[0]
    assert p["restarts"] == 0
    assert p["age"] == ""
    assert p["age_s"] is None


def test_parse_waiting_reason_crashloop():
    doc = pod_doc(
        restarts=(4,),
        status__containerStatuses=[
            {
                "restartCount": 4,
                "state": {"waiting": {"reason": "CrashLoopBackOff"}},
            }
        ],
    )
    p = parse_pod_list({"items": [doc]}, now=NOW)[0]
    assert p["reason"] == "CrashLoopBackOff"


def test_parse_oomkilled_from_last_state():
    doc = pod_doc(
        status__containerStatuses=[
            {
                "restartCount": 1,
                "state": {"running": {}},
                "lastState": {"terminated": {"reason": "OOMKilled"}},
            }
        ],
    )
    p = parse_pod_list({"items": [doc]}, now=NOW)[0]
    assert p["reason"] == "OOMKilled"


def test_completed_termination_not_a_reason():
    doc = pod_doc(
        status__containerStatuses=[
            {"restartCount": 0, "state": {"terminated": {"reason": "Completed"}}}
        ],
    )
    p = parse_pod_list({"items": [doc]}, now=NOW)[0]
    assert p["reason"] is None


def test_tpu_topology_metadata_extracted():
    doc = pod_doc(
        spec__nodeSelector={
            "cloud.google.com/gke-tpu-topology": "4x4",
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
        },
        metadata__labels={
            "jobset.sigs.k8s.io/jobset-name": "maxtext-pretrain",
            "batch.kubernetes.io/job-completion-index": "3",
        },
    )
    p = parse_pod_list({"items": [doc]}, now=NOW)[0]
    assert p["tpu_topology"] == "4x4"
    assert p["tpu_accelerator"] == "tpu-v5p-slice"
    assert p["jobset"] == "maxtext-pretrain"
    assert p["job_index"] == "3"


def test_empty_and_malformed_items():
    assert parse_pod_list({}) == []
    assert parse_pod_list({"items": [{}]})[0]["status"] == "Unknown"


def test_collector_degrades_when_all_sources_fail():
    """Reference contract: [] on error (monitor_server.js:113), with the
    error recorded."""
    c = K8sCollector(mode="api", api_url="http://127.0.0.1:1")  # nothing listens
    s = asyncio.run(c.collect())
    assert not s.ok
    assert s.data == []
    assert "ApiPodSource" in s.error


def test_tpu_request_parsed_from_resources():
    from tpumon.collectors.k8s import parse_pod_list

    pods = parse_pod_list(
        {
            "items": [
                {
                    "metadata": {"namespace": "s", "name": "tpu-pod"},
                    "spec": {
                        "containers": [
                            {"resources": {"requests": {"google.com/tpu": "4"}}},
                            {"resources": {"limits": {"google.com/tpu": "4"}}},
                            {"resources": {}},
                        ]
                    },
                    "status": {"phase": "Running"},
                },
                {
                    "metadata": {"namespace": "s", "name": "cpu-pod"},
                    "spec": {"containers": [{"resources": {"requests": {"cpu": "1"}}}]},
                    "status": {"phase": "Running"},
                },
            ]
        }
    )
    assert pods[0]["tpu_request"] == 8
    assert pods[1]["tpu_request"] == 0
