"""Self-tracing data plane (ISSUE 3): the span tracer's bounded ring and
nesting, the /api/trace summary contract, Chrome-trace/Perfetto export
schema validation (ph/ts/dur/pid/tid + child-inside-parent intervals),
bounded-ring behavior under a chaos tick storm, and the genuine
Prometheus histogram triples (_bucket with le + +Inf, _sum, _count) the
exporter now emits for stage and HTTP latency."""

import asyncio
import json

import pytest

from tests.test_server_api import serve
from tpumon.metrics_text import (
    histogram_quantile,
    parse_metrics_text,
    samples_by_name,
)
from tpumon.sampler import SourceStats
from tpumon.tracing import LatencyHistogram, SpanTracer, quantiles

# ------------------------------------------------------------- unit layer


class TestQuantiles:
    def test_single_pass_p50_p95_max(self):
        assert quantiles([5.0, 1.0, 3.0, 2.0, 4.0]) == (3.0, 4.0, 5.0)
        assert quantiles([7.0]) == (7.0, 7.0, 7.0)
        assert quantiles([]) is None

    def test_source_stats_render_all_three(self):
        st = SourceStats()
        for v in (1.0, 9.0, 2.0, 8.0, 3.0):
            st.latencies_ms.append(v)
        j = st.to_json()
        assert j["latency_p50_ms"] <= j["latency_p95_ms"] <= j["latency_max_ms"]
        assert j["latency_max_ms"] == 9.0


class TestLatencyHistogram:
    def test_cumulative_monotone_and_overflow(self):
        h = LatencyHistogram()
        for v in (0.00005, 0.003, 0.003, 7.0, 100.0):
            h.observe(v)
        cum = [c for _, c in h.cumulative()]
        assert cum == sorted(cum)
        # 100.0 is beyond the last bound: visible only in count (+Inf).
        assert cum[-1] == 4
        assert h.count == 5
        assert h.sum == pytest.approx(107.00605)


class TestSpanTracer:
    def test_ring_bounded_with_drop_accounting(self):
        tr = SpanTracer(8)
        for _ in range(20):
            with tr.span("s"):
                pass
        assert tr.recorded == 20
        assert tr.dropped == 12
        assert len(tr._spans_newest_last(100)) == 8

    def test_parent_child_nesting(self):
        tr = SpanTracer(16)
        with tr.span("parent", cat="tick"):
            with tr.span("child"):
                pass
        child, parent = tr._spans_newest_last(2)  # child closes first
        assert (child.name, parent.name) == ("child", "parent")
        assert child.parent == parent.sid
        assert parent.parent is None

    def test_disabled_tracer_records_nothing(self):
        tr = SpanTracer(0)
        with tr.span("x") as sp:
            sp.tag(a=1)  # must be a no-op, not an AttributeError
        assert not tr.enabled
        assert tr.recorded == 0
        assert tr.to_json()["spans"] == []
        assert tr.export_chrome()["traceEvents"][0]["ph"] == "M"

    def test_fleet_export_stamps_node_names_per_pid(self):
        """ISSUE 19 satellite: ``export_chrome(fleet=True)`` gives each
        node its own pid, names every process ``tpumon:<node>`` in the
        metadata (Perfetto's process list IS the fleet roster), and
        shifts remote timestamps by the per-origin clock offset."""
        tr = SpanTracer(16)
        tr.node = "root"
        tid = tr.new_trace()
        with tr.span("fed.render", trace=tid):
            pass
        tr.add_remote([
            {"name": "fed.push", "node": "leaf0", "trace": format(tid, "x"),
             "sid": 7, "parent": None, "track": "uplink",
             "ts": 1000.5, "dur_ms": 2.0, "rp": ["root", 1]},
            {"name": "fed.ingest", "node": "agg0", "trace": format(tid, "x"),
             "sid": 3, "parent": None, "track": "http",
             "ts": 1000.2, "dur_ms": 1.0},
        ])
        out = tr.export_chrome(fleet=True, offsets={"leaf0": 0.5})
        meta = {
            e["args"]["name"]: e["pid"]
            for e in out["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert meta == {"tpumon:root": 1, "tpumon:leaf0": 2,
                        "tpumon:agg0": 3}
        xs = {e["name"]: e for e in out["traceEvents"] if e["ph"] == "X"}
        assert xs["fed.push"]["pid"] == meta["tpumon:leaf0"]
        assert xs["fed.ingest"]["pid"] == meta["tpumon:agg0"]
        assert xs["fed.render"]["pid"] == 1
        # leaf0's clock runs 0.5 s ahead: its span lands at ts-0.5 on
        # the root's timeline; agg0 (no offset known) ships unshifted.
        assert xs["fed.push"]["ts"] == round(1000.0 * 1e6, 1)
        assert xs["fed.ingest"]["ts"] == round(1000.2 * 1e6, 1)
        assert xs["fed.push"]["args"]["remote_parent"] == ["root", 1]

    def test_concurrent_tasks_do_not_adopt_each_others_spans(self):
        tr = SpanTracer(64)

        async def work(name):
            with tr.span(name, cat="tick"):
                await asyncio.sleep(0.01)
                with tr.span(name + ".child"):
                    await asyncio.sleep(0.01)

        async def both():
            await asyncio.gather(work("a"), work("b"))

        asyncio.run(both())
        by = {s.name: s for s in tr._spans_newest_last(10)}
        assert by["a.child"].parent == by["a"].sid
        assert by["b.child"].parent == by["b"].sid

    def test_tick_summary_lists_direct_children(self):
        tr = SpanTracer(32)
        with tr.span("tick_fast", cat="tick"):
            with tr.span("collect.host", cat="collect"):
                pass
            with tr.span("history"):
                with tr.span("grandchild"):  # not a DIRECT child
                    pass
        names = [s["name"] for s in tr.last_tick["stages"]]
        assert names == ["collect.host", "history"]
        assert tr.last_tick["total_ms"] >= 0


# --------------------------------------------------------- live data plane


def _app(env=None):
    sampler, server = serve(env)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(sampler.tick_all())
    return loop, sampler, server


def _get(app, path, inm=None):
    loop, _, server = app
    return loop.run_until_complete(
        server.handle_ex("GET", path, if_none_match=inm)
    )


FULL_ENV = {"TPUMON_K8S_MODE": "fake", "TPUMON_SERVING_TARGETS": "fake:jetstream"}

# The acceptance set: every collector plus the alerts, history, delta
# and SSE stages must show in the per-stage summary.
EXPECTED_STAGES = (
    "tick_fast", "collect.host", "collect.accel", "collect.k8s",
    "collect.serving", "alerts", "history", "delta", "sse",
)


class TestTraceRoutes:
    @pytest.fixture()
    def app(self):
        loop, sampler, server = _app(FULL_ENV)
        yield loop, sampler, server
        loop.close()

    def _drive(self, app):
        """Exercise the whole data plane: a tick, an SSE keyframe and a
        chained delta frame."""
        loop, sampler, server = app
        _, ver, _ = server._sse_frame(-1, True)
        loop.run_until_complete(sampler.tick_fast())
        server._sse_frame(ver, False)

    def test_api_trace_covers_every_stage(self, app):
        loop, sampler, server = app
        self._drive(app)
        status, _, body, _ = _get(app, "/api/trace")
        assert status == 200
        t = json.loads(body)
        assert t["enabled"] and t["capacity"] == 4096
        for stage in EXPECTED_STAGES:
            row = t["stages"].get(stage)
            assert row is not None, f"stage {stage} missing from /api/trace"
            assert row["count"] >= 1
            assert row["p50_ms"] <= row["p95_ms"] <= row["max_ms"]
        # The strip payload: total + per-stage breakdown of the last tick.
        lt = t["last_tick"]
        assert lt["total_ms"] > 0
        names = [s["name"] for s in lt["stages"]]
        assert "collect.host" in names and "alerts" in names
        # Collect spans carry their outcome (breaker/deadline tagging).
        outcomes = [
            s["tags"].get("outcome")
            for s in t["spans"]
            if s["name"].startswith("collect.") and "tags" in s
        ]
        assert "ok" in outcomes
        # The latest device-profile capture is linked (none taken yet).
        assert t["profile"]["busy"] is False
        assert t["profile"]["captures"] == 0

    def test_api_trace_served_through_render_cache(self, app):
        loop, sampler, server = app
        _, _, body1, h1 = _get(app, "/api/trace")
        hits0 = server.cache.hits
        _, _, body2, h2 = _get(app, "/api/trace")
        assert body1 is body2  # same bytes object between ticks
        assert server.cache.hits > hits0
        assert h1["ETag"] == h2["ETag"]
        status, _, body3, _ = _get(app, "/api/trace", inm=h1["ETag"])
        assert status == 304 and body3 == b""

    def test_http_spans_summarize_per_route(self, app):
        _get(app, "/api/accel/metrics")
        _get(app, "/api/accel/metrics")
        _, _, body, _ = _get(app, "/api/trace")
        t = json.loads(body)
        row = t["http"].get("/api/accel/metrics")
        assert row is not None and row["count"] >= 2
        # Second request rode the epoch render cache: tagged as a hit.
        http_spans = [
            s for s in t["spans"]
            if s["name"] == "http"
            and s.get("tags", {}).get("route") == "/api/accel/metrics"
        ]
        assert any(s["tags"].get("cache") == "hit" for s in http_spans)
        assert all(s["tags"].get("status") == 200 for s in http_spans)

    def test_export_is_wellformed_chrome_trace(self, app):
        loop, sampler, server = app
        self._drive(app)
        _get(app, "/api/health")
        status, _, body, _ = _get(app, "/api/trace/export")
        assert status == 200
        data = json.loads(body)
        events = data["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert xs, "no complete events exported"
        for e in events:
            assert {"ph", "pid", "tid", "name"} <= set(e), e
        for e in xs:
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert e["pid"] == 1 and isinstance(e["tid"], int)
        # Metadata names the process and every track.
        metas = [e for e in events if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        tracks = {e["args"]["name"] for e in metas if e["name"] == "thread_name"}
        assert {"sampler", "http"} <= tracks
        # Child spans nest inside their parent's interval (same
        # monotonic clock => exact containment modulo the 0.1 µs
        # rounding the export applies).
        by_sid = {e["args"]["sid"]: e for e in xs}
        nested = 0
        for e in xs:
            parent = by_sid.get(e["args"].get("parent"))
            if parent is None:
                continue
            assert e["ts"] >= parent["ts"] - 0.2, (e, parent)
            assert e["ts"] + e["dur"] <= parent["ts"] + parent["dur"] + 0.2
            nested += 1
        assert nested >= 4
        # Every collector span hangs off a tick root.
        collects = [e for e in xs if e["name"].startswith("collect.")]
        assert collects
        for e in collects:
            parent = by_sid.get(e["args"]["parent"])
            assert parent is not None and parent["name"].startswith("tick")

    def test_realtime_payload_carries_trace_strip(self, app):
        loop, sampler, server = app
        payload = server.realtime_payload()
        assert payload["trace"]["total_ms"] > 0
        assert payload["trace"]["stages"]


class TestRingBoundedUnderChaosStorm:
    def test_chaos_tick_storm_stays_bounded(self):
        """A tiny ring under a fault storm (errors, slowness, breaker
        flaps) must overwrite, never grow: the tracer is part of the
        resilience story, not a new leak."""
        sampler, server = serve({
            **FULL_ENV,
            "TPUMON_TRACE_RING": "64",
            "TPUMON_CHAOS": "err:accel:0.6,slow:host:1,flap:k8s:0.5",
            "TPUMON_CHAOS_SEED": "7",
            "TPUMON_COLLECT_DEADLINE_S": "0.5",
            "TPUMON_BREAKER_FAILURES": "2",
            "TPUMON_BREAKER_BACKOFF_S": "0.05",
        })
        loop = asyncio.new_event_loop()
        try:
            for _ in range(40):
                loop.run_until_complete(sampler.tick_all())
            tr = sampler.tracer
            assert tr.recorded > 64
            assert tr.dropped == tr.recorded - 64
            status, _, body, _ = loop.run_until_complete(
                server.handle_ex("GET", "/api/trace")
            )
            t = json.loads(body)
            assert t["dropped"] > 0
            assert len(t["spans"]) <= 64
            status, _, body, _ = loop.run_until_complete(
                server.handle_ex("GET", "/api/trace/export")
            )
            xs = [
                e for e in json.loads(body)["traceEvents"] if e["ph"] == "X"
            ]
            assert len(xs) <= 64
            # Degraded collects are visible as such in the span tags.
            accel_outcomes = {
                s["tags"].get("outcome")
                for s in t["spans"]
                if s["name"] == "collect.accel" and "tags" in s
            }
            assert accel_outcomes & {"error", "skipped"}, accel_outcomes
        finally:
            loop.close()


class TestDisabledTracing:
    def test_trace_ring_zero_disables_end_to_end(self):
        loop, sampler, server = _app({"TPUMON_TRACE_RING": "0"})
        try:
            status, _, body, _ = _get((loop, sampler, server), "/api/trace")
            t = json.loads(body)
            assert t["enabled"] is False and t["spans"] == []
            assert server.realtime_payload()["trace"] is None
            _, _, body, _ = _get((loop, sampler, server), "/metrics")
            assert b"tpumon_stage_duration_seconds_bucket" not in body
            # With no per-tick trace in the payload, the SSE epoch must
            # NOT ride collection activity: unchanged data keeps
            # producing heartbeats, exactly the pre-trace behavior.
            assert "samples" not in server._rt_sections
        finally:
            loop.close()

    def test_enabled_tracing_versions_sse_on_activity(self):
        loop, sampler, server = _app()
        try:
            assert "samples" in server._rt_sections
        finally:
            loop.close()


class TestHttpRouteCardinality:
    def test_error_statuses_on_junk_paths_share_one_key(self):
        """401s (auth on) and 404s on unregistered paths must not grow
        the per-route histogram table — a URL scanner would otherwise
        fill it to its cap and pin junk labels in /metrics forever."""
        loop, sampler, server = _app({"TPUMON_AUTH_TOKEN": "s3cret"})
        try:
            from tpumon.server import HttpError

            for i in range(5):
                with pytest.raises(HttpError):  # 401: auth precedes routing
                    loop.run_until_complete(
                        server.handle_ex("POST", f"/junk-{i}", body=b"{}")
                    )
            routes = set(sampler.tracer.http_hist)
            assert not any(r.startswith("/junk") for r in routes)
            assert "(unmatched)" in routes
        finally:
            loop.close()


# ------------------------------------------------------ native histograms


class TestMetricsHistograms:
    def test_exporter_emits_genuine_histogram_triples(self):
        loop, sampler, server = _app(FULL_ENV)
        try:
            app = (loop, sampler, server)
            _get(app, "/api/health")  # seed the http histogram
            loop.run_until_complete(sampler.tick_fast())
            _, _, body, _ = _get(app, "/metrics")
            by = samples_by_name(parse_metrics_text(body.decode()))

            # Stage histogram: cumulative le-labelled buckets with +Inf,
            # _sum and _count — the text-format parser must accept it
            # and quantile estimation must work against it.
            buckets = [
                s for s in by["tpumon_stage_duration_seconds_bucket"]
                if s.labels["stage"] == "tick_fast"
            ]
            les = [s.labels["le"] for s in buckets]
            assert "+Inf" in les
            cum = [s.value for s in buckets if s.labels["le"] != "+Inf"]
            assert cum == sorted(cum)
            count = next(
                s.value for s in by["tpumon_stage_duration_seconds_count"]
                if s.labels["stage"] == "tick_fast"
            )
            inf = next(s.value for s in buckets if s.labels["le"] == "+Inf")
            assert inf == count >= 1
            total = next(
                s.value for s in by["tpumon_stage_duration_seconds_sum"]
                if s.labels["stage"] == "tick_fast"
            )
            assert total > 0
            q = histogram_quantile(buckets, 0.5)
            assert q is not None and q >= 0

            # Per-collector stage series all present.
            stages = {
                s.labels["stage"]
                for s in by["tpumon_stage_duration_seconds_count"]
            }
            assert {"collect.host", "collect.accel", "alerts", "history"} <= stages

            # HTTP histogram keyed by route.
            hb = [
                s for s in by["tpumon_http_request_duration_seconds_bucket"]
                if s.labels["route"] == "/api/health"
            ]
            assert hb and any(s.labels["le"] == "+Inf" for s in hb)

            # Profiler observability satellites.
            assert by["tpumon_profile_captures_total"][0].value == 0
            assert by["tpumon_profile_busy"][0].value == 0
            # Ring accounting.
            assert by["tpumon_trace_spans_total"][0].value >= 1

            # p95 joined p50 in the self block (single-pass quantiles).
            assert "tpumon_sample_latency_p95_ms" in by
        finally:
            loop.close()

    def test_health_reports_latency_p95(self):
        loop, sampler, server = _app()
        try:
            _, _, body, _ = _get((loop, sampler, server), "/api/health")
            h = json.loads(body)
            for src in h["sources"].values():
                assert "latency_p95_ms" in src
            assert "latency_p95_ms" in h["http"]
        finally:
            loop.close()
