"""Native host sampler: build, ABI, parity with the pure-Python reader."""

import asyncio
import shutil

import pytest

from tests.test_host_collector import LOADAVG, MEMINFO, STAT_T0, make_proc
from tpumon import native
from tpumon.collectors.host import HostCollector

needs_cxx = pytest.mark.skipif(
    shutil.which("g++") is None and not native.load(),
    reason="no g++ and no prebuilt library",
)


@needs_cxx
def test_build_and_load():
    lib = native.load(auto_build=True)
    assert lib is not None
    assert lib.tpumon_native_abi_version() == native.ABI_VERSION


@needs_cxx
def test_native_sample_real_proc():
    reader = native.make_reader()
    assert reader is not None
    s = reader.sample()
    assert s["ok_cpu"] and s["ok_mem"] and s["ok_disk"]
    assert s["cores"] >= 1
    assert s["mem_total"] > 0
    assert s["mem_available"] <= s["mem_total"]
    assert s["cpu_total_jiffies"] > s["cpu_busy_jiffies"] > 0
    assert s["disk_total"] > s["disk_used"] > 0


@needs_cxx
def test_native_matches_python_on_golden(tmp_path):
    proc = make_proc(tmp_path)
    reader = native.make_reader(proc_root=proc)
    s = reader.sample()
    assert s["load1"] == 2.45
    assert s["mem_total"] == 16384000 * 1024
    assert s["mem_available"] == 8192000 * 1024
    # busy/total must match the Python parser on the same input
    from tpumon.collectors.host import _read_proc_stat_cpu

    busy, total = _read_proc_stat_cpu(STAT_T0)
    assert (s["cpu_busy_jiffies"], s["cpu_total_jiffies"]) == (busy, total)


@needs_cxx
def test_native_degrades_per_subsource(tmp_path):
    (tmp_path / "loadavg").write_text(LOADAVG)
    (tmp_path / "stat").write_text(STAT_T0)
    # no meminfo
    reader = native.make_reader(proc_root=str(tmp_path))
    s = reader.sample()
    assert s["ok_cpu"] and not s["ok_mem"] and s["ok_disk"]


@needs_cxx
def test_collector_uses_native(tmp_path):
    proc = make_proc(tmp_path)
    c = HostCollector(cpu_count=8, proc_root=proc, use_native=True)
    assert c.native_active
    s = asyncio.run(c.collect())
    assert s.ok
    assert s.data["cpu"]["load_1min"] == 2.45
    assert s.data["memory"]["percent"] == pytest.approx(50.0, abs=0.1)


def test_collector_without_native(tmp_path):
    c = HostCollector(cpu_count=8, proc_root=make_proc(tmp_path), use_native=False)
    assert not c.native_active
    s = asyncio.run(c.collect())
    assert s.ok and s.data["cpu"]["load_1min"] == 2.45


@needs_cxx
def test_native_sampling_faster_or_comparable(tmp_path):
    """The fast path exists for the samples/sec metric; assert it's at
    least not slower than the pure-Python reader."""
    import time

    proc = make_proc(tmp_path)
    native_c = HostCollector(cpu_count=8, proc_root=proc, use_native=True)
    python_c = HostCollector(cpu_count=8, proc_root=proc, use_native=False)

    async def rate(c, n=300):
        t0 = time.perf_counter()
        for _ in range(n):
            await c.collect()
        return n / (time.perf_counter() - t0)

    native_rate = asyncio.run(rate(native_c))
    python_rate = asyncio.run(rate(python_c))
    assert native_rate > python_rate * 0.8  # allow jitter; expect >=1x
