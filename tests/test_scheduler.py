"""Chunked-prefill continuous-batching scheduler (serving.ServeConfig
scheduler/prefill_chunk_budget/admit_lookahead).

The load-bearing invariant: per-request token streams are a pure
function of (seed, prompt, params) — sampling keys fold (request id,
token index), so the sequential stop-the-world baseline and the
interleaved scheduler emit BIT-IDENTICAL streams for every request,
across dense/paged layouts and block/speculative decode modes, greedy
and seeded sampling alike. That is what makes the scheduler rework
provable rather than plausible.
"""

from __future__ import annotations

import dataclasses

import pytest

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import ServeConfig, ServingEngine

# float32 so every mode/schedule pair is bit-deterministic (the same
# contract every other engine-identity test in this tree relies on).
MODEL = ModelConfig(vocab=97, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=96,
                    compute_dtype="float32")

# Arrival trace: chunked long prompts (prefill_len=8 -> up to 8
# chunks), short prompts, a seeded-sampling request and a greedy one
# landing together — the interleavings differ per scheduler, the
# streams must not.
TRACE = [
    ([(7 * i + 3) % 97 for i in range(37)], 6, 0.0, 0),    # 5 chunks
    ([5, 1, 88], 8, 0.0, 0),
    ([(3 * i + 11) % 97 for i in range(21)], 5, 1.0, 8),   # sampled
    ([9, 2, 6, 5], 7, 0.0, 0),
    ([(11 * i + 2) % 97 for i in range(49)], 4, 0.0, 0),   # 7 chunks
    ([4, 4, 2], 6, 0.7, 12),                               # sampled
    ([8, 1, 8, 2, 8], 6, 0.0, 0),
]


def run_trace(**cfg_over) -> list[list[int]]:
    eng = ServingEngine(ServeConfig(
        model=MODEL, slots=cfg_over.pop("slots", 2), prefill_len=8,
        **cfg_over), seed=5)
    reqs = [eng.submit(p, max_new=mx, temperature=t, top_k=k)
            for p, mx, t, k in TRACE]
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    return [r.output for r in reqs]


class TestScheduleIndependence:
    """Same seed + arrival trace => bit-identical per-request streams,
    whatever the scheduler, layout, or decode mode."""

    @pytest.fixture(scope="class")
    def reference(self):
        return run_trace(scheduler="sequential")

    @pytest.mark.parametrize("over", [
        dict(scheduler="interleaved"),
        dict(scheduler="interleaved", prefill_chunk_budget=2),
        dict(scheduler="interleaved", prefill_chunk_budget=7),
        dict(scheduler="sequential", kv_layout="paged"),
        dict(scheduler="interleaved", kv_layout="paged"),
        dict(scheduler="interleaved", kv_layout="paged", pool_pages=17),
        dict(scheduler="sequential", decode_block=4),
        dict(scheduler="interleaved", decode_block=4),
        dict(scheduler="interleaved", kv_layout="paged", decode_block=4),
        dict(scheduler="sequential", spec_len=2),
        dict(scheduler="interleaved", spec_len=2),
        dict(scheduler="interleaved", kv_layout="paged", spec_len=2),
    ], ids=lambda o: "-".join(f"{k}={v}" for k, v in o.items()))
    def test_stream_matches_sequential_dense(self, reference, over):
        assert run_trace(**over) == reference

    def test_slot_count_does_not_change_streams(self, reference):
        # More slots => completely different batch compositions and
        # admission timing; the per-request streams stay put.
        assert run_trace(scheduler="interleaved", slots=4) == reference
        assert run_trace(scheduler="sequential", slots=4) == reference


class TestInterleaving:
    def test_decode_flows_while_long_prompt_prefills(self):
        """The headline behavior: with budget=1, an active request
        keeps emitting one token per step while a long prompt's chunks
        trickle in — under the sequential baseline the same admission
        runs all chunks inside one step (stop-the-world)."""
        eng = ServingEngine(ServeConfig(
            model=MODEL, slots=2, prefill_len=8, scheduler="interleaved"))
        short = eng.submit([1, 2, 3], max_new=30)
        eng.step()  # admit + first decode
        assert short.ttft_s is not None
        long_req = eng.submit([(5 * i) % 97 for i in range(48)], max_new=4)
        for _ in range(5):  # 6 chunks: still prefilling for 5 steps
            before = len(short.output)
            eng.step()
            assert long_req.ttft_s is None  # mid-prefill, budget 1
            assert len(short.output) == before + 1  # decode flowed
        eng.step()  # final chunk -> first token
        assert long_req.ttft_s is not None
        eng.drain()
        assert short.done.is_set() and long_req.done.is_set()

    def test_sequential_admission_is_stop_the_world(self):
        eng = ServingEngine(ServeConfig(
            model=MODEL, slots=2, prefill_len=8, scheduler="sequential"))
        short = eng.submit([1, 2, 3], max_new=30)
        eng.step()
        long_req = eng.submit([(5 * i) % 97 for i in range(48)], max_new=4)
        eng.step()  # whole 6-chunk prefill runs inline in this step
        assert long_req.ttft_s is not None

    def test_prefill_state_visible_in_metrics(self):
        eng = ServingEngine(ServeConfig(
            model=MODEL, slots=2, prefill_len=8, scheduler="interleaved"))
        active = eng.submit([1, 2], max_new=20)
        eng.step()
        eng.submit([(5 * i) % 97 for i in range(48)], max_new=2)
        eng.step()  # long assigned, mid-prefill
        assert "tpumon_serving_slots_prefill 1" in eng.metrics_text()
        eng.drain()
        text = eng.metrics_text()
        assert "tpumon_serving_slots_prefill 0" in text
        # Per-request latency gauges appear once requests completed.
        assert "tpumon_serving_ttft_p50_ms" in text
        assert "tpumon_serving_ttft_p95_ms" in text
        assert "tpumon_serving_tpot_p50_ms" in text  # active decoded >1
        assert active.done.is_set()

    def test_latency_gauges_distill(self):
        from tpumon.collectors.serving import distill_serving_metrics

        eng = ServingEngine(ServeConfig(model=MODEL, slots=2,
                                        prefill_len=8))
        eng.submit([3, 1, 4], max_new=6)
        eng.drain()
        d = distill_serving_metrics(eng.metrics_text())
        assert d["ttft_p95_ms"] >= d["ttft_p50_ms"] > 0
        assert d["tpot_p95_ms"] >= d["tpot_p50_ms"] > 0
        assert d["slots_prefill"] == 0

    def test_cancel_mid_prefill_releases_and_counts_cancelled(self):
        eng = ServingEngine(ServeConfig(
            model=MODEL, slots=2, prefill_len=8, scheduler="interleaved",
            kv_layout="paged"))
        free0 = eng.allocator.free_pages
        blocker = eng.submit([1, 2], max_new=25)
        eng.step()
        victim = eng.submit([(5 * i) % 97 for i in range(48)], max_new=4)
        eng.step()  # victim assigned, mid-prefill (pages reserved)
        assert eng.allocator.free_pages < free0 - 4
        victim.cancel()
        eng.step()
        assert victim.done.is_set() and victim.output == []
        assert eng.cancelled_total == 1  # not a completion: no token out
        blocker.cancel()
        eng.drain()
        assert eng.allocator.free_pages == free0


class TestLookaheadAdmission:
    """Paged admission lookahead: a request whose prefix is fully
    cached (near-zero new pages) must not starve behind a page-blocked
    head — but the head must not starve either (aging bound)."""

    PREFIX = [7, 1, 8, 2, 8, 1, 8, 2]  # one chunk at prefill_len=8

    def engine(self, lookahead=0, max_skips=8, pool_pages=12, slots=2):
        return ServingEngine(ServeConfig(
            model=MODEL, slots=slots, prefill_len=8, kv_layout="paged",
            pool_pages=pool_pages, prefix_cache_entries=4,
            scheduler="sequential", admit_lookahead=lookahead,
            admit_max_skips=max_skips))

    def seed_prefix(self, eng):
        r = eng.submit(self.PREFIX + [3, 3], max_new=2)
        eng.drain()
        assert r.done.is_set()
        return r

    def hog_and_head(self, eng):
        """Occupy most of the pool with a long-running request, then
        queue a head that cannot reserve."""
        hog = eng.submit([(3 * i) % 97 for i in range(30)], max_new=40)
        eng.step()
        assert hog.ttft_s is not None
        head = eng.submit([(11 * i + 1) % 97 for i in range(30)],
                          max_new=40)
        eng.step()
        assert head.ttft_s is None  # blocked on pages
        return hog, head

    def test_fifo_blocks_cached_candidate_without_lookahead(self):
        eng = self.engine(lookahead=0)
        self.seed_prefix(eng)
        hog, head = self.hog_and_head(eng)
        cand = eng.submit(self.PREFIX + [9, 9], max_new=1)
        for _ in range(6):
            eng.step()
        assert cand.ttft_s is None  # strict FIFO: waits behind the head
        hog.cancel()
        eng.drain()
        assert head.done.is_set() and cand.done.is_set()

    def test_lookahead_admits_cached_candidate_past_blocked_head(self):
        eng = self.engine(lookahead=2)
        self.seed_prefix(eng)
        hog, head = self.hog_and_head(eng)
        cand = eng.submit(self.PREFIX + [9, 9], max_new=1)
        for _ in range(6):
            eng.step()
        assert cand.done.is_set()  # jumped the page-blocked head
        assert head.ttft_s is None
        assert eng._head_skips == 1
        hog.cancel()
        eng.drain()
        assert head.done.is_set()
        assert eng._head_skips == 0  # head admission resets the age

    def test_aged_head_is_force_next_under_sustained_hits(self):
        """Sustained prefix-hit traffic keeps jumping the queue — but
        only admit_max_skips times; then the window collapses to the
        head until it admits (nothing starves)."""
        eng = self.engine(lookahead=4, max_skips=2)
        self.seed_prefix(eng)
        hog, head = self.hog_and_head(eng)
        cands = [eng.submit(self.PREFIX + [9, i], max_new=1)
                 for i in range(5)]
        for _ in range(20):
            eng.step()
        served_early = [c for c in cands if c.done.is_set()]
        assert len(served_early) == 2  # the aging bound, exactly
        assert eng._head_skips == 2
        hog.cancel()
        eng.drain()
        # Head admitted before the remaining candidates (sequential
        # scheduler: admission order == TTFT order).
        assert head.done.is_set()
        late = [c for c in cands if c not in served_early]
        assert all(c.done.is_set() for c in late)
        assert all(head.ttft_s < c.ttft_s for c in late)

    def test_cancelled_aged_head_does_not_poison_successor(self):
        """An aged-out head that gets cancelled must not bequeath its
        suspended lookahead window to the next head — the skip count is
        pinned to the head's request id and resets on succession."""
        eng = self.engine(lookahead=4, max_skips=2)
        self.seed_prefix(eng)
        hog, head = self.hog_and_head(eng)
        head2 = eng.submit([(13 * i + 2) % 97 for i in range(30)],
                           max_new=40)  # blocked too, right behind head
        first = [eng.submit(self.PREFIX + [9, i], max_new=1)
                 for i in range(2)]
        for _ in range(8):
            eng.step()
        assert all(c.done.is_set() for c in first)
        assert eng._head_skips == 2  # head aged out
        head.cancel()
        eng.step()  # purge; head2 takes the head slot with a fresh age
        second = [eng.submit(self.PREFIX + [8, i], max_new=1)
                  for i in range(2)]
        for _ in range(8):
            eng.step()
        assert all(c.done.is_set() for c in second)  # window restored
        assert head2.ttft_s is None
        hog.cancel()
        eng.drain()
        assert head2.done.is_set()

    def test_head_eviction_cannot_evict_its_own_prefix(self):
        """Freeing pages FOR the queue head must not evict the prefix
        the head is about to share, even when that entry is the LRU one
        — the pre-scheduler lookup-first admission protected it via
        retain+LRU-touch; the peek-based scheduler protects it by name
        (PagePrefixCache.evict_one(protect=...))."""
        # Pool: 1 trash + 9 usable. Two cached prefixes pin 1 page
        # each; a filler request then occupies the rest, so admitting a
        # prefix-sharing head forces an eviction.
        eng = self.engine(pool_pages=10, slots=2)
        self.seed_prefix(eng)                       # PREFIX entry (LRU-first)
        other = [9, 9, 9, 9, 9, 9, 9, 9]
        r2 = eng.submit(other + [1, 1], max_new=2)  # second entry (MRU)
        eng.drain()
        assert r2.done.is_set() and eng.prefix_cache.entries == 2
        # Filler reserves all 7 remaining pages (20+36 rows -> 7 pages).
        filler = eng.submit([(3 * i) % 97 for i in range(20)], max_new=36)
        eng.step()
        assert filler.ttft_s is not None
        assert eng.allocator.free_pages == 0
        hits0 = eng.prefix_cache.hits
        # Head shares PREFIX (the LRU entry): needs one page beyond its
        # shared chunk, so an eviction must free it — the OTHER entry
        # must go, not the head's own.
        head = eng.submit(self.PREFIX + [5] * 4, max_new=2)
        eng.step()
        assert head.ttft_s is not None  # admitted (eviction freed pages)
        assert eng.prefix_cache.hits == hits0 + 1  # the hit survived
        assert tuple(self.PREFIX) in eng.prefix_cache._store
        filler.cancel()
        eng.drain()

    def test_lookahead_streams_are_schedule_independent(self):
        """Queue-jumping changes admission ORDER, never streams."""
        outs = {}
        for la in (0, 2):
            eng = self.engine(lookahead=la, pool_pages=12)
            self.seed_prefix(eng)
            hog, head = self.hog_and_head(eng)
            cand = eng.submit(self.PREFIX + [9, 9], max_new=4)
            for _ in range(4):
                eng.step()
            hog.cancel()
            eng.drain()
            outs[la] = (head.output, cand.output)
        assert outs[0] == outs[2]


class TestPeek:
    def test_page_prefix_peek_is_side_effect_free(self):
        from tpumon.loadgen.paged_kv import PageAllocator, PagePrefixCache

        alloc = PageAllocator(8)
        pc = PagePrefixCache(chunk=4, allocator=alloc, max_entries=4)
        pages = alloc.alloc(3)
        prompt = list(range(10))  # strict prefix = 8 tokens = 2 pages
        pc.store(prompt, pages)
        free_before = alloc.free_pages
        m, shared = pc.peek(prompt)
        assert m == 8 and shared == pages[:2]
        # No retain, no counters, no LRU churn — probe leaves no trace.
        assert alloc.free_pages == free_before
        assert alloc._refs[pages[0]] == 2  # store's pin only
        assert pc.hits == 0 and pc.misses == 0 and pc.saved_tokens == 0
        assert pc.peek([55, 66, 77, 88, 99]) == (0, [])
        assert pc.misses == 0
        # The real lookup still counts and retains.
        m2, shared2 = pc.lookup(prompt)
        assert (m2, shared2) == (m, shared)
        assert pc.hits == 1 and alloc._refs[pages[0]] == 3

    def test_dense_prefix_peek_matches_restore_probe(self):
        eng = ServingEngine(ServeConfig(
            model=MODEL, slots=2, prefill_len=8, prefix_cache_entries=4))
        prompt = [7, 1, 8, 2, 8, 1, 8, 2, 5, 5]
        eng.submit(prompt, max_new=2)
        eng.drain()
        pc = eng.prefix_cache
        hits, misses = pc.hits, pc.misses
        assert pc.peek(prompt) == 8
        assert pc.peek([1, 2, 3]) == 0
        assert (pc.hits, pc.misses) == (hits, misses)


class TestConfigValidation:
    @pytest.mark.parametrize("over,msg", [
        (dict(scheduler="bogus"), "scheduler"),
        (dict(prefill_chunk_budget=0), "prefill_chunk_budget"),
        (dict(admit_lookahead=-1), "admit_lookahead"),
        (dict(admit_lookahead=2), "paged"),  # dense never blocks
        (dict(admit_max_skips=0), "admit_max_skips"),
    ])
    def test_rejected(self, over, msg):
        with pytest.raises(ValueError, match=msg):
            ServingEngine(ServeConfig(model=MODEL, **over))

    def test_start_background_passthrough(self):
        from tpumon.loadgen.serving import start_background

        eng, url, stop = start_background(
            rps=0.0, scheduler="sequential", prefill_budget=3,
            admit_lookahead=2, kv_layout="paged")
        try:
            assert eng.cfg.scheduler == "sequential"
            assert eng.cfg.prefill_chunk_budget == 3
            assert eng.cfg.admit_lookahead == 2
        finally:
            stop.set()


# ---------------------------------------------------------------------------
# dp×tp mesh golden matrix + ring-attention admission (mesh serving)
# ---------------------------------------------------------------------------

# tp=2 shards heads/vocab/ffn over the "model" axis, so every sharded
# dim must divide 2 — same scale as MODEL with vocab 96, not prime 97.
MESH_MODEL = dataclasses.replace(MODEL, vocab=96)


def run_mesh_trace(dp: int, tp: int, **cfg_over) -> list[list[int]]:
    from tpumon.loadgen.serving import make_serving_engine

    eng = make_serving_engine(ServeConfig(
        model=MESH_MODEL, slots=cfg_over.pop("slots", 2), prefill_len=8,
        mesh_dp=dp, mesh_tp=tp, **cfg_over), seed=5)
    reqs = [eng.submit(p, max_new=mx, temperature=t, top_k=k)
            for p, mx, t, k in TRACE]
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    return [r.output for r in reqs]


class TestMeshGoldenMatrix:
    """The golden contract across shard layouts: every request's
    sampled stream is a pure function of (seed, prompt, params) — the
    router owns the rid namespace and all replicas share seed/params,
    so dp=1/tp=1, dp=2/tp=2 and dp=4/tp=1 emit BIT-IDENTICAL streams
    (greedy AND seeded: TRACE carries both), across dense/paged
    layouts and block/spec decode modes. CPU fake mesh (conftest
    forces 8 host devices), f32."""

    @pytest.fixture(scope="class")
    def reference(self):
        return run_mesh_trace(1, 1)

    @pytest.mark.parametrize("dp,tp,over", [
        (2, 2, dict()),
        (4, 1, dict()),
        (2, 2, dict(kv_layout="paged")),
        (4, 1, dict(kv_layout="paged")),
        (2, 2, dict(kv_layout="paged", decode_block=4)),
        (4, 1, dict(decode_block=4)),
        (2, 2, dict(kv_layout="paged", spec_len=2)),
        (4, 1, dict(spec_len=2)),
    ], ids=lambda v: ("-".join(f"{k}={x}" for k, x in v.items()) or "dense"
                      if isinstance(v, dict) else str(v)))
    def test_stream_matches_single_device(self, reference, dp, tp, over):
        assert run_mesh_trace(dp, tp, **over) == reference


class TestRingAdmission:
    """Ring-attention engine mode (ServeConfig.ring_stripes): the
    admission boundary moves from max_seq to stripes×max_seq, and the
    admitted stream is bit-identical to an unsharded engine big enough
    to hold the context flat."""

    BASE = ServeConfig(model=MODEL, slots=2, prefill_len=8,
                       kv_layout="paged")
    LONG = [(7 * i + 3) % 97 for i in range(MODEL.max_seq + 20)]

    def wide_ref(self, temperature=0.0, top_k=0):
        wide = ServingEngine(dataclasses.replace(
            self.BASE, model=dataclasses.replace(
                MODEL, max_seq=2 * MODEL.max_seq)), seed=5)
        r = wide.submit(self.LONG, max_new=4, temperature=temperature,
                        top_k=top_k)
        wide.drain()
        return r.output

    def test_flat_refuses_ring_admits_same_stream(self):
        flat = ServingEngine(self.BASE, seed=5)
        r = flat.submit(self.LONG, max_new=4)
        assert r.status == "rejected" and r.output == []
        ring = ServingEngine(dataclasses.replace(
            self.BASE, ring_stripes=2), seed=5)
        r2 = ring.submit(self.LONG, max_new=4)
        ring.drain()
        assert r2.status == "completed"
        assert r2.output == self.wide_ref()

    def test_ring_seeded_stream_matches_unsharded(self):
        ring = ServingEngine(dataclasses.replace(
            self.BASE, ring_stripes=2), seed=5)
        r = ring.submit(self.LONG, max_new=4, temperature=1.0, top_k=8)
        ring.drain()
        assert r.status == "completed"
        assert r.output == self.wide_ref(temperature=1.0, top_k=8)

    def test_blockwise_ring_attend_matches_gather(self):
        """paged_attn="ring" streams pages through the online-softmax
        accumulator instead of one fused gather; greedy decode picks
        the same tokens (the accumulation reassociates the reduction,
        so this pins argmax agreement, not bitwise logits)."""
        ring = ServingEngine(dataclasses.replace(
            self.BASE, ring_stripes=2, paged_attn="ring"), seed=5)
        r = ring.submit(self.LONG, max_new=4)
        ring.drain()
        assert r.status == "completed"
        assert r.output == self.wide_ref()


class TestMeshConfigValidation:
    @pytest.mark.parametrize("over,msg", [
        (dict(mesh_dp=0), "mesh_dp"),
        (dict(mesh_dp=2), "MeshServingEngine"),
        (dict(ring_stripes=1), "ring_stripes"),
        (dict(ring_stripes=2), "paged"),  # dense has no pages
        (dict(ring_stripes=2, kv_layout="paged", spec_len=2),
         "speculative"),
        (dict(ring_stripes=2, kv_layout="paged", paged_attn="kernel"),
         "kernel"),
        (dict(kv_layout="paged", paged_attn="ring", kv_dtype="int8"),
         "ring"),
    ])
    def test_plain_engine_rejects(self, over, msg):
        with pytest.raises(ValueError, match=msg):
            ServingEngine(ServeConfig(model=MODEL, **over))

    def test_mesh_shape_must_divide_device_count(self):
        from tpumon.loadgen.serving import MeshServingEngine

        # 8 fake devices (conftest): 3x1 neither fills nor tiles.
        with pytest.raises(ValueError, match="divide"):
            MeshServingEngine(ServeConfig(
                model=MESH_MODEL, slots=2, prefill_len=8,
                mesh_dp=3, mesh_tp=1))

    def test_factory_picks_engine_shape(self):
        from tpumon.loadgen.serving import (
            MeshServingEngine, make_serving_engine)

        cfg = ServeConfig(model=MESH_MODEL, slots=2, prefill_len=8)
        assert isinstance(make_serving_engine(cfg), ServingEngine)
        eng = make_serving_engine(
            dataclasses.replace(cfg, mesh_dp=2, mesh_tp=1))
        assert isinstance(eng, MeshServingEngine)
        assert eng.replica_ids == ("r0", "r1")
