import pytest

from tpumon import protowire as pw
from tpumon.collectors.libtpu_grpc import encode_metric_request, extract_gauges


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2**32, 2**63 - 1):
        buf = pw.encode_varint(v)
        out, pos = pw.decode_varint(buf, 0)
        assert out == v and pos == len(buf)


def test_negative_int64_two_complement():
    buf = pw.encode_varint(-1)
    assert len(buf) == 10  # canonical proto encoding of -1
    out, _ = pw.decode_varint(buf, 0)
    assert out == 2**64 - 1


def test_string_and_message_roundtrip():
    inner = pw.encode_int(1, 3) + pw.encode_double(2, 42.5)
    outer = pw.encode_string(1, "tpu.metric") + pw.encode_message(2, inner)
    msg = pw.decode_message(outer)
    assert msg.first(1) == "tpu.metric"
    sub = msg.first(2)
    assert isinstance(sub, pw.Message)
    assert sub.first(1) == 3
    assert sub.first(2) == 42.5


def test_truncated_raises():
    with pytest.raises(ValueError):
        pw.decode_message(b"\x08")  # tag then missing varint
    with pytest.raises(ValueError):
        pw.decode_message(b"\x0a\x05ab")  # length 5, only 2 bytes


def build_metric_response(values: dict[int, float], as_int=False) -> bytes:
    """Build a libtpu-shaped MetricResponse:
    MetricResponse{ metric=1: TPUMetric{ name=1, metrics=2: repeated
    Metric{ attribute=1: {key=1,value=2:{int_attr=1}}, gauge=2:{as_int=1|as_double=2} } } }
    """
    entries = b""
    for idx, val in values.items():
        attr_value = pw.encode_int(1, idx)
        attribute = pw.encode_string(1, "device_id") + pw.encode_message(2, attr_value)
        gauge = pw.encode_int(1, int(val)) if as_int else pw.encode_double(2, val)
        metric = pw.encode_message(1, attribute) + pw.encode_message(2, gauge)
        entries += pw.encode_message(2, metric)
    tpumetric = pw.encode_string(1, "tpu.runtime.hbm.memory.usage.bytes") + entries
    return pw.encode_message(1, tpumetric)


def test_extract_gauges_double():
    resp = build_metric_response({0: 12.5, 3: 99.0})
    assert extract_gauges(resp) == {0: 12.5, 3: 99.0}


def test_extract_gauges_int64():
    resp = build_metric_response({0: 8 * 2**30, 1: 4 * 2**30}, as_int=True)
    out = extract_gauges(resp)
    assert out[0] == float(8 * 2**30)
    assert out[1] == float(4 * 2**30)


def test_metric_request_shape():
    req = encode_metric_request("tpu.runtime.tensorcore.dutycycle.percent")
    msg = pw.decode_message(req)
    assert msg.first(1) == "tpu.runtime.tensorcore.dutycycle.percent"
