import pytest

from tpumon import protowire as pw
from tpumon.collectors.libtpu_grpc import encode_metric_request, extract_gauges


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2**32, 2**63 - 1):
        buf = pw.encode_varint(v)
        out, pos = pw.decode_varint(buf, 0)
        assert out == v and pos == len(buf)


def test_negative_int64_two_complement():
    buf = pw.encode_varint(-1)
    assert len(buf) == 10  # canonical proto encoding of -1
    out, _ = pw.decode_varint(buf, 0)
    assert out == 2**64 - 1


def test_string_and_message_roundtrip():
    inner = pw.encode_int(1, 3) + pw.encode_double(2, 42.5)
    outer = pw.encode_string(1, "tpu.metric") + pw.encode_message(2, inner)
    msg = pw.decode_message(outer)
    assert msg.first(1) == "tpu.metric"
    sub = msg.first(2)
    assert isinstance(sub, pw.Message)
    assert sub.first(1) == 3
    assert sub.first(2) == 42.5


def test_truncated_raises():
    with pytest.raises(ValueError):
        pw.decode_message(b"\x08")  # tag then missing varint
    with pytest.raises(ValueError):
        pw.decode_message(b"\x0a\x05ab")  # length 5, only 2 bytes


def build_metric_response(values: dict[int, float], as_int=False) -> bytes:
    """Build a libtpu-shaped MetricResponse:
    MetricResponse{ metric=1: TPUMetric{ name=1, metrics=2: repeated
    Metric{ attribute=1: {key=1,value=2:{int_attr=1}}, gauge=2:{as_int=1|as_double=2} } } }
    """
    entries = b""
    for idx, val in values.items():
        attr_value = pw.encode_int(1, idx)
        attribute = pw.encode_string(1, "device_id") + pw.encode_message(2, attr_value)
        gauge = pw.encode_int(1, int(val)) if as_int else pw.encode_double(2, val)
        metric = pw.encode_message(1, attribute) + pw.encode_message(2, gauge)
        entries += pw.encode_message(2, metric)
    tpumetric = pw.encode_string(1, "tpu.runtime.hbm.memory.usage.bytes") + entries
    return pw.encode_message(1, tpumetric)


def test_extract_gauges_double():
    resp = build_metric_response({0: 12.5, 3: 99.0})
    assert extract_gauges(resp) == {0: 12.5, 3: 99.0}


def test_extract_gauges_int64():
    resp = build_metric_response({0: 8 * 2**30, 1: 4 * 2**30}, as_int=True)
    out = extract_gauges(resp)
    assert out[0] == float(8 * 2**30)
    assert out[1] == float(4 * 2**30)


def test_metric_request_shape():
    req = encode_metric_request("tpu.runtime.tensorcore.dutycycle.percent")
    msg = pw.decode_message(req)
    assert msg.first(1) == "tpu.runtime.tensorcore.dutycycle.percent"


# ---------------------- delta stream frames (federation wire) -----------


def _evolving_table(t: int) -> list[list]:
    """A chips_to_wire-shaped table exercising every column coder AND
    ctype churn: nulls toggling, strings changing, an int column that
    flips to floats and back, variable-length coords."""
    rows = []
    for i in range(10):
        rows.append([
            f"h{i // 4}/c{i % 4}",                       # str (stable)
            f"h{i // 4}",                                # str dict
            None if (i + t) % 5 == 0 else 10.5 + i + t,  # f64 w/ nulls
            2**40 + i * t,                               # i64
            [i % 4, i // 4, 0] if i != 7 else [],        # intlists
            (None, True, False)[(i + t) % 3],            # bool w/ nulls
            "fake" if (i + t) % 2 else None,             # str w/ nulls
            2**63 - 1 - t,                               # i64 extreme
        ])
    if t % 4 == 3:
        for r in rows:
            r[2] = 7  # whole column becomes int: ctype change
    return rows


_DELTA_FIELDS = ["id", "host", "duty", "hbm", "coords", "flag", "src", "ctr"]


def test_delta_stream_replay_bit_exact():
    """Keyframe + deltas replay EXACTLY (values and types) what a full
    frame of each tick's table decodes to — including across ctype
    changes, null toggles and the periodic keyframe cadence."""
    enc = pw.DeltaStreamEncoder(keyframe_every=6)
    dec = pw.DeltaStreamDecoder()
    keys = 0
    for t in range(20):
        rows = _evolving_table(t)
        frame, was_key = enc.encode(1, _DELTA_FIELDS, rows, ts=1000.0 + t)
        keys += was_key
        res = dec.apply(frame)
        assert res["ts"] == 1000.0 + t and res["key"] == was_key
        _, _, ref = pw.decode_wire_frame(
            pw.encode_wire_frame(1, _DELTA_FIELDS, rows)
        )
        assert res["cols"] == ref
        for got, want in zip(res["cols"], ref):
            for a, b in zip(got, want):
                assert type(a) is type(b), (t, a, b)
    # Cadence: first frame + every 6th (20 frames => 1 + 3 rescheduled).
    assert keys == 4 and dec.keyframes == 4
    # This table deliberately churns almost every cell; even so a delta
    # never exceeds its keyframe.
    st = enc.stats
    assert st["delta_bytes"] / st["delta_frames"] < st["keyframe_bytes"]


def test_delta_stream_steady_state_is_small():
    """On a realistic chip table — identity/topology columns stable,
    only the duty column moving — steady-state deltas are <= 25% of a
    keyframe (the federation bench's per-tick upstream-bytes claim)."""
    fields = ["id", "host", "slice", "kind", "coords", "duty", "hbm_total"]
    def rows_at(t):
        return [
            [f"h{i // 4}/c{i % 4}", f"h{i // 4}", f"s{i // 32}", "v5p",
             [i % 4, i // 4, 0], 50.0 + ((i * 7 + t * 13) % 100) / 10.0,
             95 * 2**30]
            for i in range(64)
        ]
    enc = pw.DeltaStreamEncoder(keyframe_every=10_000)
    dec = pw.DeltaStreamDecoder()
    dec.apply(enc.encode(1, fields, rows_at(0), ts=1.0)[0])
    for t in range(1, 12):
        dec.apply(enc.encode(1, fields, rows_at(t), ts=1.0 + t)[0])
    st = enc.stats
    assert st["delta_bytes"] / st["delta_frames"] <= 0.25 * st["keyframe_bytes"]
    _, _, ref = pw.decode_wire_frame(pw.encode_wire_frame(1, fields, rows_at(11)))
    assert dec.cols == ref


def test_delta_stream_shape_changes_force_keyframe():
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    dec = pw.DeltaStreamDecoder()
    rows = _evolving_table(0)
    dec.apply(enc.encode(1, _DELTA_FIELDS, rows, ts=1.0)[0])
    # Row count change (chip arrived/left) => keyframe, not a diff.
    frame, was_key = enc.encode(1, _DELTA_FIELDS, rows[:-1], ts=2.0)
    assert was_key
    dec.apply(frame)
    # Field-list change => keyframe.
    f2 = _DELTA_FIELDS + ["extra"]
    rows2 = [r + [1] for r in rows[:-1]]
    frame, was_key = enc.encode(1, f2, rows2, ts=3.0)
    assert was_key and dec.apply(frame)["fields"] == f2
    # reset() (transport reconnect) => keyframe resync.
    enc.reset()
    frame, was_key = enc.encode(1, f2, rows2, ts=4.0)
    assert was_key


def test_delta_stream_gap_and_desync_raise():
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    dec = pw.DeltaStreamDecoder()
    k, _ = enc.encode(1, _DELTA_FIELDS, _evolving_table(0), ts=1.0)
    d1, _ = enc.encode(1, _DELTA_FIELDS, _evolving_table(1), ts=2.0)
    d2, _ = enc.encode(1, _DELTA_FIELDS, _evolving_table(2), ts=3.0)
    # Delta before any keyframe: refused.
    with pytest.raises(ValueError):
        pw.DeltaStreamDecoder().apply(d1)
    dec.apply(k)
    # Skipping d1 is a sequence gap: refused (transport resyncs).
    with pytest.raises(ValueError):
        dec.apply(d2)
    # The failed apply did not corrupt state: d1 then d2 still work.
    dec.apply(d1)
    dec.apply(d2)
    # Junk magic is refused too.
    with pytest.raises(ValueError):
        dec.apply(b"XXXX" + d1[4:])


def test_delta_stream_truncation_raises_at_every_prefix():
    """Same harness as the PR 6 wire tests: EVERY truncation prefix of
    a keyframe and of a delta frame must raise ValueError — and must
    raise BEFORE mutating decoder state (two-phase apply)."""
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    key, _ = enc.encode(1, _DELTA_FIELDS, _evolving_table(0), ts=1.0)
    delta, was_key = enc.encode(1, _DELTA_FIELDS, _evolving_table(1), ts=2.0)
    assert not was_key
    for blob in (key, delta):
        for cut in range(len(blob)):
            dec = pw.DeltaStreamDecoder()
            dec.apply(key)
            before = [list(c) for c in dec.cols]
            with pytest.raises(ValueError):
                dec.apply(blob[:cut])
            assert dec.cols == before  # atomic: no half-applied state
            # ...and the stream recovers from where it was.
            dec.apply(delta)


def test_wire_frame_truncation_at_every_prefix_all_ctypes():
    """Exhaustive column-type coverage (pinned by tpulint's wire pass):
    one column per _CT_* type, classification asserted per column, then
    the frame round-trips exactly and EVERY truncation prefix raises
    ValueError — a new column type cannot ship without its short-read
    behavior being exercised."""
    # column name -> (values, expected ctype). Six rows, nulls mixed in.
    table = {
        "f64": ([0.1, None, 2.25, 3.0, -0.5, 1e300], pw._CT_F64),
        "f32": ([1.5, None, 2.25, -0.5, 3.0, 0.0], pw._CT_F32),
        "i64": ([1, None, -5, 2**62, 0, -(2**63)], pw._CT_I64),
        "big": ([2**65, None, -(2**65), 1, 0, 5], pw._CT_VARINT),
        "s": (["a", None, "b", "a", "", "c"], pw._CT_STR),
        "b": ([True, None, False, True, False, True], pw._CT_BOOL),
        "ilf": (
            [[1, 2, 3], None, [4, 5, 6], [7, 8, 9], [0, 0, 0], [1, 1, 1]],
            pw._CT_INTLIST_FIXED,
        ),
        "il": ([[1], None, [2, 3], [], [2**40], [5]], pw._CT_INTLIST),
        "none": ([None] * 6, pw._CT_NONE),
    }
    fields = list(table)
    for name, (col, want) in table.items():
        assert pw._classify(col, allow_f32=True) == want, name
    rows = [
        [table[f][0][i] for f in fields] for i in range(6)
    ]
    frame = pw.encode_wire_frame(1, fields, rows, allow_f32=True)
    v, got_fields, cols = pw.decode_wire_frame(frame)
    assert v == 1 and got_fields == fields
    for name, got in zip(fields, cols):
        want = table[name][0]
        # int-valued cells may come back as lists (tuples encode as
        # lists); everything else round-trips exactly, types included.
        assert [list(x) if isinstance(x, tuple) else x for x in want] == got
    for cut in range(len(frame)):
        with pytest.raises(ValueError):
            pw.decode_wire_frame(frame[:cut])


def test_delta_stream_empty_diff_is_tiny_heartbeat():
    """An unchanged table produces a near-empty delta (liveness ride)."""
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    dec = pw.DeltaStreamDecoder()
    rows = _evolving_table(1)
    dec.apply(enc.encode(1, _DELTA_FIELDS, rows, ts=1.0)[0])
    frame, was_key = enc.encode(1, _DELTA_FIELDS, rows, ts=2.0)
    assert not was_key and len(frame) < 32
    res = dec.apply(frame)
    _, _, ref = pw.decode_wire_frame(pw.encode_wire_frame(1, _DELTA_FIELDS, rows))
    assert res["cols"] == ref


def test_delta_stream_intlist_row_goes_none():
    """A fixed-stride int-list cell flipping to None while its
    neighbors stay put: the all-None sub-column must encode (as
    _CT_NONE) rather than producing a stride-0 frame the decoder
    refuses — regression for the encoder/decoder mismatch."""
    fields = ["id", "coords", "duty"]
    rows = [[f"c{i}", [i, 0, 0], 1.0 + i] for i in range(6)]
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    dec = pw.DeltaStreamDecoder()
    dec.apply(enc.encode(1, fields, rows, ts=1.0)[0])
    rows2 = [list(r) for r in rows]
    rows2[3] = ["c3", None, 1.0 + 3]  # ONLY the coords cell changes
    frame, was_key = enc.encode(1, fields, rows2, ts=2.0)
    assert not was_key
    res = dec.apply(frame)
    _, _, ref = pw.decode_wire_frame(pw.encode_wire_frame(1, fields, rows2))
    assert res["cols"] == ref
    # ...and back to a list again.
    rows3 = [list(r) for r in rows2]
    rows3[3] = ["c3", [9, 9, 9], 1.0 + 3]
    res = dec.apply(enc.encode(1, fields, rows3, ts=3.0)[0])
    _, _, ref = pw.decode_wire_frame(pw.encode_wire_frame(1, fields, rows3))
    assert res["cols"] == ref


# ------------- accel_kind wire column back-compat (ISSUE 15) ------------


def test_wire_frame_with_accel_kind_truncation_at_every_prefix():
    """The appended accel_kind column (topology.WIRE_FIELDS[-1]) rides
    the real chip frame: build one from live fake chips (TPU + GPU so
    the string dictionary has two entries), round-trip it, and raise
    ValueError at EVERY truncation prefix — the same harness the other
    ctypes are pinned under."""
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.collectors.gpu_fake import FakeGpuCollector
    from tpumon.topology import WIRE_FIELDS, chips_from_wire, chips_to_wire

    chips = (
        FakeTpuCollector(topology="v5e-4", clock=lambda: 1000.0).chips()
        + FakeGpuCollector(topology="dgx-a100-8", clock=lambda: 1000.0).chips()
    )
    w = chips_to_wire(chips)
    assert w["fields"] == list(WIRE_FIELDS)
    assert w["fields"][-1] == "accel_kind"
    ak = w["fields"].index("accel_kind")
    assert {row[ak] for row in w["rows"]} == {"tpu", "gpu"}
    frame = pw.encode_wire_frame(w["v"], w["fields"], w["rows"])
    v, fields, cols = pw.decode_wire_frame(frame)
    assert fields[-1] == "accel_kind"
    assert cols[-1] == [row[ak] for row in w["rows"]]
    assert chips_from_wire({"v": v, "fields": fields,
                            "rows": [list(r) for r in zip(*cols)]}) == chips
    for cut in range(len(frame)):
        with pytest.raises(ValueError):
            pw.decode_wire_frame(frame[:cut])


def test_pre_accel_kind_peer_frames_decode_unchanged():
    """Back-compat regression (ISSUE 15 satellite): a pre-accel_kind
    peer's JSON payload and binary frame — checked in as fixtures, NOT
    re-generated, so an encoder change can't silently launder a wire
    break — decode to the same chips as today's encoder, every chip
    defaulting to accel_kind='tpu'. Bit-exactness both ways: today's
    encoder over the old field list reproduces the old frame byte for
    byte."""
    import base64
    import json
    import os

    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.topology import chips_from_wire, chips_to_wire

    path = os.path.join(
        os.path.dirname(__file__), "fixtures", "wire_pre_accel_kind.json"
    )
    with open(path) as f:
        fix = json.load(f)
    old_frame = base64.b64decode(fix["frame_b64"])

    # Binary and JSON forms agree with each other...
    v, fields, cols = pw.decode_wire_frame(old_frame)
    assert [v, fields] == [fix["json_wire"]["v"], fix["json_wire"]["fields"]]
    assert "accel_kind" not in fields
    chips = chips_from_wire(fix["json_wire"])
    assert chips == chips_from_wire(
        {"v": v, "fields": fields, "rows": [list(r) for r in zip(*cols)]}
    )
    # ...default the appended column...
    assert chips and all(c.accel_kind == "tpu" for c in chips)
    # ...match what the fixture's generator collector produces today
    # (same chips, modulo the appended field the old peer couldn't say)...
    today = FakeTpuCollector(topology="v5e-4", clock=lambda: 1000.0).chips()
    assert chips == today
    # ...and today's encoder over the old layout is bit-exact with the
    # checked-in frame (append-only really did leave the prefix alone).
    w = chips_to_wire(today)
    old_rows = [row[:-1] for row in w["rows"]]
    assert pw.encode_wire_frame(w["v"], w["fields"][:-1], old_rows) == old_frame


def test_delta_stream_from_pre_accel_kind_sender_replays():
    """A pre-upgrade LEAF keeps streaming TPWK/TPWD frames in the old
    16-field layout; the decoder replays them bit-exactly and the
    materialized chips default to accel_kind='tpu' — old peers
    federate/merge unchanged."""
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.topology import chips_from_columns, chips_to_wire

    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    dec = pw.DeltaStreamDecoder()
    for t in (1000.0, 1001.0, 1002.0):
        chips = FakeTpuCollector(topology="v5e-4", clock=lambda: t).chips()
        w = chips_to_wire(chips)
        old_fields = w["fields"][:-1]
        old_rows = [r[:-1] for r in w["rows"]]
        frame, _ = enc.encode(w["v"], old_fields, old_rows, ts=t)
        res = dec.apply(frame)
        got = chips_from_columns(res["fields"], res["cols"])
        assert got == chips  # accel_kind defaulted to "tpu" everywhere
        assert all(c.accel_kind == "tpu" for c in got)


# ------------- leadership generation trailer (ISSUE 16, root HA) --------


def _fake_wire(ts: float):
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.topology import chips_to_wire

    return chips_to_wire(
        FakeTpuCollector(topology="v5e-4", clock=lambda: ts).chips()
    )


def _load_pre_generation_fixture():
    import base64
    import json
    import os

    path = os.path.join(
        os.path.dirname(__file__), "fixtures", "wire_pre_generation.json"
    )
    with open(path) as f:
        fix = json.load(f)
    return fix, {
        k: base64.b64decode(fix[f"{k}_b64"])
        for k in ("keyframe", "delta", "query_req", "query_res")
    }


def test_generation_trailer_roundtrip_all_frame_types():
    """All four frame types carry the trailing generation varint and
    decode it back; the decoder remembers the sender's generation."""
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    enc.generation = 7
    dec = pw.DeltaStreamDecoder()
    for ts in (1000.0, 1001.0):
        w = _fake_wire(ts)
        frame, was_key = enc.encode(w["v"], w["fields"], w["rows"], ts=ts)
        res = dec.apply(frame)
        assert res["generation"] == 7 and dec.generation == 7
    # Generation can only move the way fencing needs it to: up.
    enc.generation = 300  # 2-byte varint: exercises multi-byte trailers
    w = _fake_wire(1002.0)
    res = dec.apply(enc.encode(w["v"], w["fields"], w["rows"], ts=1002.0)[0])
    assert res["generation"] == 300 and dec.generation == 300

    req = pw.encode_query_request(9, "fleet(duty)", 1.0, 2.0, generation=300)
    assert pw.decode_query_request(req) == (
        9, "fleet(duty)", 1.0, 2.0, 300, None
    )
    res = pw.encode_query_result(9, {"kind": "scalar"}, generation=300)
    qid, partial, error, payload, gen, trace = pw.decode_query_result(res)
    assert (qid, partial, error, gen, trace) == (9, False, None, 300, None)
    assert payload == {"kind": "scalar"}


def test_pre_generation_fixture_decodes_and_reencodes_bit_exact():
    """Back-compat pinned both directions by checked-in frames (never
    re-generated): a pre-upgrade peer's TPWK/TPWD/TPWQ/TPWR decode
    unchanged with generation 0, and today's encoder at generation 0
    reproduces every one of them byte for byte — the trailer really is
    append-only and conditional."""
    fix, frames = _load_pre_generation_fixture()

    dec = pw.DeltaStreamDecoder()
    res = dec.apply(frames["keyframe"])
    assert res["key"] and res["generation"] == 0 and dec.generation == 0
    res = dec.apply(frames["delta"])
    assert not res["key"] and res["generation"] == 0

    q = fix["query_req"]
    assert pw.decode_query_request(frames["query_req"]) == (
        q["qid"], q["expr"], q["at"], q["timeout_s"], 0, None
    )
    r = fix["query_res"]
    qid, partial, error, payload, gen, trace = pw.decode_query_result(
        frames["query_res"]
    )
    assert (qid, partial, error, gen, trace) == (
        r["qid"], r["partial"], None, 0, None
    )
    assert payload == r["payload"]

    # Today's encoder, generation 0 (the default): bit-exact re-encode.
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    assert enc.generation == 0
    for ts, name in ((1000.0, "keyframe"), (1001.0, "delta")):
        w = _fake_wire(ts)
        frame, _ = enc.encode(w["v"], w["fields"], w["rows"], ts=ts)
        assert frame == frames[name], name
    assert pw.encode_query_request(
        q["qid"], q["expr"], q["at"], q["timeout_s"]
    ) == frames["query_req"]
    assert pw.encode_query_result(
        r["qid"], r["payload"], partial=r["partial"]
    ) == frames["query_res"]


def test_pre_generation_fixture_truncation_at_every_prefix():
    """The no-trailer fixture frames stay fully guarded: EVERY
    truncation prefix of all four pre-upgrade frames raises ValueError
    (and the stream decoder stays atomic, same as the PR 6 harness)."""
    _, frames = _load_pre_generation_fixture()
    for blob in (frames["keyframe"], frames["delta"]):
        for cut in range(len(blob)):
            dec = pw.DeltaStreamDecoder()
            dec.apply(frames["keyframe"])
            before = [list(c) for c in dec.cols]
            with pytest.raises(ValueError):
                dec.apply(blob[:cut])
            assert dec.cols == before
    for cut in range(len(frames["query_req"])):
        with pytest.raises(ValueError):
            pw.decode_query_request(frames["query_req"][:cut])
    for cut in range(len(frames["query_res"])):
        with pytest.raises(ValueError):
            pw.decode_query_result(frames["query_res"][:cut])


def test_generation_stamped_truncation_skips_trailer_boundary():
    """A gen-stamped frame truncated at EXACTLY the trailer boundary is
    a VALID pre-upgrade frame (that is what append-only means) — it
    decodes as generation 0. Every other prefix still raises."""
    _, frames = _load_pre_generation_fixture()
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    enc.generation = 3  # 1-byte varint trailer
    kg, _ = enc.encode(*_unpack(_fake_wire(1000.0)), ts=1000.0)
    dg, was_key = enc.encode(*_unpack(_fake_wire(1001.0)), ts=1001.0)
    assert not was_key
    # Strictly appended: strip the trailer and the fixture bytes emerge.
    assert kg[:-1] == frames["keyframe"] and dg[:-1] == frames["delta"]
    for blob in (kg, dg):
        boundary = len(blob) - 1
        for cut in range(len(blob)):
            dec = pw.DeltaStreamDecoder()
            dec.apply(kg)
            if cut == boundary:
                assert dec.apply(blob[:cut])["generation"] == 0
                continue
            with pytest.raises(ValueError):
                dec.apply(blob[:cut])

    req = pw.encode_query_request(7, "x", 1.0, 2.0, generation=3)
    assert req[:-1] == pw.encode_query_request(7, "x", 1.0, 2.0)
    assert pw.decode_query_request(req[:-1])[4] == 0
    res = pw.encode_query_result(7, {"a": 1}, generation=3)
    assert res[:-1] == pw.encode_query_result(7, {"a": 1})
    assert pw.decode_query_result(res[:-1])[4] == 0


def _unpack(w):
    return w["v"], w["fields"], w["rows"]


def test_replay_onto_promoted_standby_is_bit_exact():
    """Failover at the wire level: an active root has consumed a long
    keyframe+delta history; the uplink rotates to a freshly promoted
    standby and resyncs with one keyframe (encoder reset). The standby's
    materialized table must equal the active root's — bit-exact through
    a re-encode — with the new leader's generation riding the resync."""
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    enc.generation = 1
    active = pw.DeltaStreamDecoder()
    for t in range(8):
        w = _fake_wire(1000.0 + t)
        active.apply(enc.encode(*_unpack(w), ts=1000.0 + t)[0])
    # Root dies; standby promotes (generation 2); transport reconnects.
    enc.reset()
    enc.generation = 2
    standby = pw.DeltaStreamDecoder()
    w = _fake_wire(1007.0)  # same tick the active root last saw
    frame, was_key = enc.encode(*_unpack(w), ts=1007.0)
    assert was_key
    res = standby.apply(frame)
    assert res["generation"] == 2 and standby.generation == 2
    assert standby.cols == active.cols
    assert standby.fields == active.fields
    # Bit-exact: both states re-encode to identical keyframes.
    def reencode(dec):
        e = pw.DeltaStreamEncoder(keyframe_every=1)
        rows = [list(r) for r in zip(*dec.cols)]
        return e.encode(1, dec.fields, rows, ts=5.0)[0]
    assert reencode(standby) == reencode(active)


# ------------- trace context trailer (ISSUE 19, fleet tracing) ----------


def _load_gen_pre_trace_fixture():
    import base64
    import json
    import os

    path = os.path.join(
        os.path.dirname(__file__), "fixtures", "wire_gen_pre_trace.json"
    )
    with open(path) as f:
        fix = json.load(f)
    return fix, {
        k: base64.b64decode(fix[f"{k}_b64"])
        for k in ("keyframe", "delta", "query_req", "query_res")
    }


def test_trace_trailer_roundtrip_all_frame_types():
    """All four frame types carry the optional trace context after the
    generation and decode it back — including at generation 0, where
    the generation varint is emitted explicitly so the trace fields
    stay positionally unambiguous."""
    ctx = (0xABCDEF0123, 42, "leaf0")
    for gen in (0, 7, 300):
        enc = pw.DeltaStreamEncoder(keyframe_every=1000)
        enc.generation = gen
        enc.trace = ctx
        dec = pw.DeltaStreamDecoder()
        for ts in (1000.0, 1001.0):  # keyframe, then delta
            res = dec.apply(enc.encode(*_unpack(_fake_wire(ts)), ts=ts)[0])
            assert res["generation"] == gen and res["trace"] == ctx
            assert dec.trace == ctx
        req = pw.encode_query_request(
            9, "fleet(duty)", 1.0, 2.0, generation=gen, trace=ctx
        )
        assert pw.decode_query_request(req) == (
            9, "fleet(duty)", 1.0, 2.0, gen, ctx
        )
        res = pw.encode_query_result(
            9, {"kind": "scalar"}, generation=gen, trace=ctx
        )
        out = pw.decode_query_result(res)
        assert (out[0], out[4], out[5]) == (9, gen, ctx)
    # Clearing the context restores the pre-trace layout mid-stream.
    enc.trace = None
    res = dec.apply(enc.encode(*_unpack(_fake_wire(1002.0)), ts=1002.0)[0])
    assert res["trace"] is None and dec.trace is None


def test_gen_pre_trace_fixture_decodes_and_reencodes_bit_exact():
    """ISSUE-16-era back-compat pinned both directions by checked-in
    frames (never re-generated): a generation-stamped pre-trace peer's
    TPWK/TPWD/TPWQ/TPWR decode unchanged (generation kept, trace None),
    and today's encoder with tracing off reproduces every one byte for
    byte — the trace trailer really is append-only and conditional, so
    tracing off adds ZERO wire bytes."""
    fix, frames = _load_gen_pre_trace_fixture()
    gen = fix["generation"]

    dec = pw.DeltaStreamDecoder()
    res = dec.apply(frames["keyframe"])
    assert res["key"] and res["generation"] == gen and res["trace"] is None
    res = dec.apply(frames["delta"])
    assert not res["key"] and res["generation"] == gen
    assert res["trace"] is None and dec.trace is None

    q = fix["query_req"]
    assert pw.decode_query_request(frames["query_req"]) == (
        q["qid"], q["expr"], q["at"], q["timeout_s"], gen, None
    )
    r = fix["query_res"]
    qid, partial, error, payload, rgen, trace = pw.decode_query_result(
        frames["query_res"]
    )
    assert (qid, partial, error, rgen, trace) == (
        r["qid"], r["partial"], None, gen, None
    )
    assert payload == r["payload"]

    # Today's encoder, trace None (the default, and always when tracing
    # is off): bit-exact re-encode of the pre-trace frames.
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    enc.generation = gen
    assert enc.trace is None
    for ts, name in ((1000.0, "keyframe"), (1001.0, "delta")):
        frame, _ = enc.encode(*_unpack(_fake_wire(ts)), ts=ts)
        assert frame == frames[name], name
    assert pw.encode_query_request(
        q["qid"], q["expr"], q["at"], q["timeout_s"], generation=gen
    ) == frames["query_req"]
    assert pw.encode_query_result(
        r["qid"], r["payload"], partial=r["partial"], generation=gen
    ) == frames["query_res"]


def test_gen_pre_trace_fixture_truncation_at_every_prefix():
    """Truncation guard over the gen-stamped fixture frames: every cut
    raises EXCEPT the single append-only boundary at the start of the
    generation varint (a valid pre-generation frame), and the stream
    decoder stays atomic across refused frames."""
    fix, frames = _load_gen_pre_trace_fixture()
    ngen = len(pw.encode_varint(fix["generation"]))
    assert ngen == 2  # multi-byte: cuts INSIDE the varint must raise
    for blob in (frames["keyframe"], frames["delta"]):
        boundary = len(blob) - ngen
        for cut in range(len(blob)):
            dec = pw.DeltaStreamDecoder()
            dec.apply(frames["keyframe"])
            before = [list(c) for c in dec.cols]
            if cut == boundary:
                assert dec.apply(blob[:cut])["generation"] == 0
                continue
            with pytest.raises(ValueError):
                dec.apply(blob[:cut])
            assert dec.cols == before
    for name, decode in (
        ("query_req", pw.decode_query_request),
        ("query_res", pw.decode_query_result),
    ):
        blob = frames[name]
        boundary = len(blob) - ngen
        for cut in range(len(blob)):
            if cut == boundary:
                assert decode(blob[:cut])[4] == 0
                continue
            with pytest.raises(ValueError):
                decode(blob[:cut])


def test_trace_stamped_truncation_skips_both_trailer_boundaries():
    """A trace-stamped frame has exactly TWO valid truncation points —
    end of payload (pre-generation layout) and end of the generation
    varint (pre-trace layout); every cut inside the trace context
    itself raises, and the stream decoder stays atomic."""
    ctx = (0x1234, 5, "leaf0")
    gen = 3
    trailer = pw.encode_trailers(gen, ctx)
    enc = pw.DeltaStreamEncoder(keyframe_every=1000)
    enc.generation = gen
    enc.trace = ctx
    kg, _ = enc.encode(*_unpack(_fake_wire(1000.0)), ts=1000.0)
    dg, was_key = enc.encode(*_unpack(_fake_wire(1001.0)), ts=1001.0)
    assert not was_key
    for blob in (kg, dg):
        base = len(blob) - len(trailer)
        gen_end = base + len(pw.encode_varint(gen))
        for cut in range(len(blob)):
            dec = pw.DeltaStreamDecoder()
            dec.apply(kg)
            before = [list(c) for c in dec.cols]
            if cut in (base, gen_end):
                res = dec.apply(blob[:cut])
                assert res["generation"] == (0 if cut == base else gen)
                assert res["trace"] is None
                continue
            with pytest.raises(ValueError):
                dec.apply(blob[:cut])
            assert dec.cols == before

    req = pw.encode_query_request(7, "x", 1.0, 2.0, generation=gen, trace=ctx)
    base = len(req) - len(trailer)
    gen_end = base + len(pw.encode_varint(gen))
    assert req[:base] == pw.encode_query_request(7, "x", 1.0, 2.0)
    for cut in range(len(req)):
        if cut in (base, gen_end):
            out = pw.decode_query_request(req[:cut])
            assert out[4] == (0 if cut == base else gen) and out[5] is None
            continue
        with pytest.raises(ValueError):
            pw.decode_query_request(req[:cut])


def test_trace_origin_bounded_both_directions():
    ok = (1, 2, "x" * pw.TRACE_ORIGIN_MAX)
    assert pw.decode_query_request(
        pw.encode_query_request(1, "e", 0.0, 1.0, trace=ok)
    )[5] == ok
    with pytest.raises(ValueError):
        pw.encode_query_request(
            1, "e", 0.0, 1.0, trace=(1, 2, "x" * (pw.TRACE_ORIGIN_MAX + 1))
        )
    # Hand-crafted hostile trailer: implausible origin length refused.
    base = pw.encode_query_request(1, "e", 0.0, 1.0)
    evil = base + pw.encode_varint(0) + pw.encode_varint(1) + \
        pw.encode_varint(2) + pw.encode_varint(pw.TRACE_ORIGIN_MAX + 1)
    with pytest.raises(ValueError):
        pw.decode_query_request(evil)


def test_trace_span_relay_frame_roundtrip_and_truncation():
    """TPWS span-relay records roundtrip and refuse truncation/garbage
    everywhere — same record discipline as the query frames they ride
    the ingest stream with."""
    payload = {
        "node": "agg0",
        "spans": [
            {"sid": 3, "parent": None, "name": "fed.push", "dur_ms": 1.5},
        ],
        "offsets": {"leaf0": 12.25},
    }
    blob = pw.encode_trace_spans(payload)
    assert blob[:4] == pw.TRACE_SPANS_MAGIC
    assert pw.decode_trace_spans(blob) == payload
    for cut in range(len(blob)):
        with pytest.raises(ValueError):
            pw.decode_trace_spans(blob[:cut])
    with pytest.raises(ValueError):
        pw.decode_trace_spans(blob + b"x")
    with pytest.raises(ValueError):
        pw.encode_trace_spans({"spans": ["y" * pw.TRACE_SPANS_MAX]})
