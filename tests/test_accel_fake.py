import asyncio

import pytest

from tpumon.collectors.accel_fake import FAKE_TOPOLOGIES, FakeTpuCollector


def test_topologies_shapes():
    for topo, (kind, hosts, per_host, hosts_per_slice) in FAKE_TOPOLOGIES.items():
        c = FakeTpuCollector(topology=topo, clock=lambda: 1000.0)
        chips = c.chips()
        assert len(chips) == hosts * per_host, topo
        assert all(ch.kind == kind for ch in chips)
        assert len({ch.chip_id for ch in chips}) == len(chips)  # unique ids
        n_slices = -(-hosts // hosts_per_slice)
        assert len({ch.slice_id for ch in chips}) == n_slices, topo


def test_pod_of_pods_slice_labels():
    """v5p-512/v5p-2048 are pod-of-pods: every chip carries a per-slice
    label (the federation rollup key), slices are 256 chips each, and
    a host never straddles two slices."""
    for topo, n_slices in (("v5p-512", 2), ("v5p-2048", 8)):
        chips = FakeTpuCollector(topology=topo, clock=lambda: 1000.0).chips()
        by_slice: dict = {}
        for ch in chips:
            by_slice.setdefault(ch.slice_id, []).append(ch)
        assert len(by_slice) == n_slices, topo
        assert all(len(v) == 256 for v in by_slice.values())
        for sid, group in by_slice.items():
            hosts = {c.host for c in group}
            for other, og in by_slice.items():
                if other != sid:
                    assert hosts.isdisjoint({c.host for c in og})
    # Single-slice shapes keep the configured slice_id verbatim.
    c = FakeTpuCollector(topology="v5e-8", slice_id="mypod")
    assert {ch.slice_id for ch in c.chips()} == {"mypod"}


def test_v5e8_values_in_range():
    c = FakeTpuCollector(topology="v5e-8", clock=lambda: 1234.5)
    for ch in c.chips():
        assert 0 <= ch.mxu_duty_pct <= 100
        assert 0 < ch.hbm_used <= ch.hbm_total
        assert ch.hbm_total == 16 * 1024**3
        assert 30 < ch.temp_c < 90
        assert ch.ici_tx_bytes > 0 and ch.ici_link_up


def test_deterministic_given_time():
    a = FakeTpuCollector(topology="v5e-8", clock=lambda: 500.0).chips()
    b = FakeTpuCollector(topology="v5e-8", clock=lambda: 500.0).chips()
    assert [c.mxu_duty_pct for c in a] == [c.mxu_duty_pct for c in b]


def test_ici_counters_monotonic():
    t = [100.0]
    c = FakeTpuCollector(topology="v5e-1", clock=lambda: t[0])
    first = c.chips()[0].ici_tx_bytes
    t[0] = 110.0
    second = c.chips()[0].ici_tx_bytes
    assert second > first


def test_kill_host_fault_injection():
    c = FakeTpuCollector(topology="v5p-64")
    assert len(c.chips()) == 64
    c.kill_host("tpu-host-3")
    chips = c.chips()
    assert len(chips) == 60
    assert not any(ch.host == "tpu-host-3" for ch in chips)
    c.revive_host("tpu-host-3")
    assert len(c.chips()) == 64


def test_override_injection():
    c = FakeTpuCollector(topology="v5e-8")
    cid = "tpu-host-0/chip-2"
    c.set_override(cid, mxu_duty_pct=0.5, ici_link_up=False)
    chips = {ch.chip_id: ch for ch in c.chips()}
    assert chips[cid].mxu_duty_pct == 0.5
    assert chips[cid].ici_link_up is False


def test_unknown_topology_rejected():
    with pytest.raises(ValueError):
        FakeTpuCollector(topology="v99-1")


def test_collect_sample_envelope():
    s = asyncio.run(FakeTpuCollector(topology="v5e-4").collect())
    assert s.ok and s.source == "accel" and len(s.data) == 4


def test_fault_episodes():
    """+faults: deterministic periodic degradation episodes for demo
    mode — chip 3's link degrades ~60s/8min, chip 5 throttles
    ~45s/11min; outside episodes everything is healthy."""
    from tpumon.collectors.accel import make_accel_collector
    from tpumon.config import load_config

    c = make_accel_collector(
        load_config(env={"TPUMON_ACCEL_BACKEND": "fake:v5e-8+faults"})
    )
    assert c.fault_episodes
    c.clock = lambda: 30.0  # inside both episode windows
    by_idx = {ch.index: ch for ch in c.chips()}
    assert by_idx[3].ici_link_health == 7
    # 5 is the lowest score past the strict '>' serious threshold
    # (TriLevel(0, 4, 7)) so the demo exercises the serious alert.
    assert by_idx[5].throttle_score == 5
    from tpumon.config import Thresholds

    assert Thresholds().throttle_score.severity(by_idx[5].throttle_score) == "serious"
    assert by_idx[0].ici_link_health == 0
    c.clock = lambda: 200.0  # between episodes
    assert all(ch.ici_link_health == 0 for ch in c.chips())
    assert all(ch.throttle_score == 0 for ch in c.chips())
    # Plain spec (no +faults) stays always-healthy.
    plain = make_accel_collector(
        load_config(env={"TPUMON_ACCEL_BACKEND": "fake:v5e-8"})
    )
    plain.clock = lambda: 30.0
    assert all(ch.ici_link_health == 0 for ch in plain.chips())


def test_jax_collector_init_hang_degrades():
    """A wedged device runtime must degrade the sample, not hang the
    monitor (regression for the lost-remote-grant scenario)."""
    import time as _time

    from tpumon.collectors.accel_jax import JaxTpuCollector

    # Wedge shorter than it looks: asyncio.run's shutdown JOINS the
    # default executor, so the test pays the full simulated hang after
    # the timeout fires — 1.5s proves the 0.2s timeout without the
    # 30s tail this test used to cost the suite.
    c = JaxTpuCollector(init_timeout_s=0.2)
    c._init_devices = lambda: _time.sleep(1.5)  # simulated wedge
    s = asyncio.run(c.collect())
    assert not s.ok
    assert s.data == []
    assert "hung" in s.error
