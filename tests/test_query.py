"""In-tree query engine (ISSUE 12 tentpole, tpumon/query.py):

- parser/lexer error surface;
- topology labels derived from series naming;
- GOLDEN PARITY: every expression form evaluated by the engine must be
  bit-compatible with an independent brute-force reference over the
  checked-in TSDB fuzz corpus (tests/fixtures/tsdb_fuzz.json — the same
  corpus the codec golden tests ride);
- recording rules: state bit-exact between the native kernel and the
  pure-Python fallback, O(1) reads proven by making the point store
  raise, bounded divergence vs the direct path;
- QSketch merge laws and the partial/merge/finalize distributed
  algebra's local equivalence;
- the env-predicate compiler's alerting None semantics;
- /api/query[_range] routes + the `tpumon query` CLI.
"""

import asyncio
import json
import math
import os

import pytest

from tpumon import tsdb
from tpumon.history import RingHistory
from tpumon.query import (
    QSketch,
    QueryEngine,
    QueryError,
    RecordingRule,
    RuleSet,
    _quantile,
    compile_env,
    parse,
    parse_series_name,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "tsdb_fuzz.json")


# ------------------------------ parsing --------------------------------


def test_parser_accepts_the_documented_forms():
    for src in (
        "mxu",
        "chip.hbm",
        "rate(chip.hbm[1m])",
        "rate(chip.hbm)",
        "avg_over_time(mxu[30s])",
        "quantile_over_time(0.95, chip.mxu[5m])",
        "topk(5, rate(chip.hbm[1m]))",
        "avg by (host) (chip.mxu)",
        "avg(chip.mxu) by (host, pod)",
        'chip.hbm{chip="h0/c1", host=~"h*"}',
        "quantile(0.5, chip.mxu) * 2 + 1",
        "chip.mxu > 50 and chip.hbm < 90",
        "-(avg(chip.mxu)) / 2",
    ):
        parse(src)


@pytest.mark.parametrize(
    "src",
    [
        "",
        "   ",
        "rate(",
        "rate()",
        "topk(chip.mxu)",  # k must be a number literal
        "avg(chip.mxu",
        "chip.hbm[",
        "chip.hbm[banana]",
        'chip.hbm{chip=h0}',  # matcher value must be a string
        "chip.hbm{chip~\"x\"}",
        "avg by host (chip.mxu)",  # by wants parens
        "quantile(chip.mxu)",
        "and",
        "avg(chip.mxu)) ",
        "avg(1)",  # scalar into an aggregation is a QueryError at eval
    ],
)
def test_parser_and_eval_errors_are_query_errors(src):
    ring = RingHistory(1800)
    ring.record("chip.h/c0.mxu", 1.0, ts=1000.0)
    with pytest.raises(QueryError):
        QueryEngine(ring).instant(src, at=1000.0)


def test_series_name_labels():
    assert parse_series_name("cpu") == ("cpu", {})
    assert parse_series_name("chip.h0/c3.hbm") == (
        "chip.hbm",
        {"chip": "h0/c3", "host": "h0"},
    )
    fam, labels = parse_series_name("slice.leaf0.slice-0.duty_p95")
    assert fam == "slice.duty_p95"
    assert labels == {"node": "leaf0", "slice": "slice-0"}


# ------------------------- golden parity suite -------------------------
#
# The reference below is an INDEPENDENT naive implementation of the
# documented semantics (docs/query.md): closed window [t-w, t],
# reset-aware increase, rate over the actual point span, interpolated
# quantiles, series sorted by name, aggregation folds in that order.
# Values must match the engine bit-for-bit (== on floats, no tolerance).


def load_corpus_ring() -> tuple[RingHistory, dict[str, list[tuple[float, float]]]]:
    """The fuzz corpus as chip.<case>/c.<metric> series (labels exercise
    the chip/host derivation), plus the plain point lists the reference
    evaluates over. Points replayed through the normal ingest path, so
    what the reference sees is exactly what the store holds."""
    with open(FIXTURE) as f:
        corpus = json.load(f)
    ring = RingHistory(window_s=10**9, long_window_s=10**9)
    flat: dict[str, list[tuple[float, float]]] = {}
    for i, case in enumerate(corpus):
        name = f"chip.{case['name']}/c{i}.mxu"
        for t_ms, v in zip(case["ts_ms"], case["values"]):
            if v != v or v in (float("inf"), float("-inf")):
                continue  # instant vectors drop non-finite (render contract)
            ring.record(name, v, ts=t_ms / 1000.0)
        pts = sorted(ring.series[name].fine.since(None)) if name in ring.series else []
        flat[name] = pts
    return ring, flat


def ref_window(pts, at, w):
    return [v for t, v in pts if at - w <= t <= at]


def ref_instant(pts, at, lookback=300.0):
    older = [(t, v) for t, v in pts if t <= at and t >= at - lookback]
    return older[-1][1] if older else None


def ref_range_fn(fn, q, pts, at, w):
    win = [(t, v) for t, v in pts if at - w <= t <= at]
    vals = [v for _, v in win]
    if not vals:
        return None
    if fn == "avg_over_time":
        return sum(vals) / len(vals)
    if fn == "sum_over_time":
        return sum(vals)
    if fn == "min_over_time":
        return min(vals)
    if fn == "max_over_time":
        return max(vals)
    if fn == "count_over_time":
        return float(len(vals))
    if fn == "quantile_over_time":
        return _quantile(sorted(vals), q)
    if len(vals) < 2:
        return None
    inc = 0.0
    for i in range(1, len(vals)):
        d = vals[i] - vals[i - 1]
        inc += d if d >= 0 else vals[i]
    if fn == "increase":
        return inc
    span = win[-1][0] - win[0][0]
    return inc / span if span > 0 else None


def test_engine_matches_brute_force_reference():
    ring, flat = load_corpus_ring()
    engine = QueryEngine(ring)
    names = sorted(flat)
    ats = []
    for pts in flat.values():
        if pts:
            ats.append(pts[-1][0])
    at = max(ats)

    # instant selector
    got = engine.instant("chip.mxu", at=at)["result"]
    want = [
        (parse_series_name(n)[1], ref_instant(flat[n], at))
        for n in names
        if ref_instant(flat[n], at) is not None
    ]
    assert [(r["labels"], r["value"]) for r in got] == want

    # every range function, several windows
    for fn in (
        "rate", "increase", "avg_over_time", "min_over_time",
        "max_over_time", "sum_over_time", "count_over_time",
    ):
        for w in (30.0, 120.0, 3600.0):
            got = engine.instant(f"{fn}(chip.mxu[{int(w)}s])", at=at)["result"]
            want = []
            for n in names:
                v = ref_range_fn(fn, None, flat[n], at, w)
                if v is not None:
                    want.append((parse_series_name(n)[1], v))
            assert [(r["labels"], r["value"]) for r in got] == want, (fn, w)

    for qv in (0.0, 0.5, 0.9, 1.0):
        got = engine.instant(
            f"quantile_over_time({qv}, chip.mxu[300s])", at=at
        )["result"]
        want = []
        for n in names:
            v = ref_range_fn("quantile_over_time", qv, flat[n], at, 300.0)
            if v is not None:
                want.append((parse_series_name(n)[1], v))
        assert [(r["labels"], r["value"]) for r in got] == want, qv

    # aggregations over the instant vector, grouped and ungrouped
    vec = [
        (parse_series_name(n)[1], ref_instant(flat[n], at))
        for n in names
        if ref_instant(flat[n], at) is not None
    ]
    vals = [v for _, v in vec]
    cases = {
        "sum(chip.mxu)": sum(vals),
        "avg(chip.mxu)": sum(vals) / len(vals),
        "min(chip.mxu)": min(vals),
        "max(chip.mxu)": max(vals),
        "count(chip.mxu)": float(len(vals)),
        "quantile(0.5, chip.mxu)": _quantile(sorted(vals), 0.5),
    }
    for src, want_v in cases.items():
        got = engine.instant(src, at=at)["result"]
        assert len(got) == 1 and got[0]["value"] == want_v, src

    got = engine.instant("avg by (host) (chip.mxu)", at=at)["result"]
    groups: dict[str, list[float]] = {}
    for labels, v in vec:
        groups.setdefault(labels["host"], []).append(v)
    want = [
        {"labels": {"host": h}, "value": sum(g) / len(g)}
        for h, g in sorted(groups.items())
    ]
    assert got == want

    # topk/bottomk: value-ordered, full labels, deterministic ties
    got = engine.instant("topk(3, chip.mxu)", at=at)["result"]
    srt = sorted(vec, key=lambda p: (p[1], tuple(sorted(p[0].items()))),
                 reverse=True)
    assert [(r["labels"], r["value"]) for r in got] == srt[:3]

    # arithmetic and filtering comparison
    got = engine.instant("avg(chip.mxu) * 2 - 1", at=at)["result"]
    assert got[0]["value"] == (sum(vals) / len(vals)) * 2 - 1
    med = _quantile(sorted(vals), 0.5)
    got = engine.instant(f"chip.mxu > {med!r}", at=at)["result"]
    want = [(lb, v) for lb, v in vec if v > med]
    assert [(r["labels"], r["value"]) for r in got] == want


def test_range_query_matches_per_step_instants():
    ring, flat = load_corpus_ring()
    engine = QueryEngine(ring)
    at = max(pts[-1][0] for pts in flat.values() if pts)
    rq = engine.range_query("avg_over_time(chip.mxu[60s])", 300, 60, end=at)
    for s in rq["series"]:
        for t, v in s["points"]:
            one = engine.instant("avg_over_time(chip.mxu[60s])", at=t)
            by_labels = {
                tuple(sorted(r["labels"].items())): r["value"]
                for r in one["result"]
            }
            assert by_labels[tuple(sorted(s["labels"].items()))] == v


# --------------------------- recording rules ---------------------------


def _rules_ring(n_chips=8, ticks=400, kernel=True):
    tsdb.set_kernel_enabled(kernel)
    tsdb._KERNEL_TRIED = False
    tsdb._KERNEL = None
    ring = RingHistory()
    ring.set_recording_rules(
        RuleSet([RecordingRule("chip.mxu[5m]"), RecordingRule("chip.hbm[5m]")])
    )
    hs = [
        ring.handle(f"chip.h{c % 2}/c{c}.{m}")
        for c in range(n_chips)
        for m in ("mxu", "hbm", "temp")
    ]
    now = 1_700_000_000.0
    for i in range(ticks):
        ring.record_batch(
            [(h, 30.0 + (j * 3 + i) % 60) for j, h in enumerate(hs)],
            ts=now + i,
        )
    return ring, now + ticks - 1


def teardown_module():
    tsdb.set_kernel_enabled(True)
    tsdb._KERNEL_TRIED = False
    tsdb._KERNEL = None


def test_rule_state_kernel_vs_python_bit_exact():
    ring_k, _ = _rules_ring(kernel=True)
    ring_p, _ = _rules_ring(kernel=False)
    for rk, rp in zip(ring_k.rules.rules, ring_p.rules.rules):
        for col in ("hh", "open", "hist", "slot_map"):
            assert (
                getattr(rk.store, col).tobytes()
                == getattr(rp.store, col).tobytes()
            ), col


def test_rule_reads_never_walk_points():
    """The acceptance criterion: a rule-backed instant read is an O(1)
    merge of head-state rows — proven by making the point store raise
    if anything decodes a window."""
    ring, at = _rules_ring()
    engine = QueryEngine(ring)
    orig = tsdb.Tier.since
    def boom(self, start):
        raise AssertionError("rule-backed read walked the point store")
    tsdb.Tier.since = boom
    try:
        for src in (
            "avg_over_time(chip.mxu[5m])",
            "max_over_time(chip.hbm[5m])",
            "min_over_time(chip.mxu[5m])",
            "sum_over_time(chip.hbm[5m])",
            "count_over_time(chip.mxu[5m])",
            "rate(chip.mxu[5m])",
            "increase(chip.hbm[5m])",
            "topk(3, avg_over_time(chip.mxu[5m]))",
        ):
            out = engine.instant(src, at=at)["result"]
            assert out and all(r["value"] is not None for r in out), src
    finally:
        tsdb.Tier.since = orig


def test_rule_reads_agree_with_direct_path():
    """Rule reads are window-quantized (the oldest overlapping
    sub-bucket is whole — span in [w, w+w/16)): count/min/max are exact
    over that span and sum/avg/rate differ from a point walk only by
    float association. Check against a direct evaluation over the
    rule's effective window."""
    ring, at = _rules_ring()
    engine = QueryEngine(ring)
    rule = ring.rules.rules[0]
    b_lo = (at - rule.window_s) // rule.sub_s
    eff_w = at - b_lo * rule.sub_s  # the bucket-quantized span
    for fn in ("avg_over_time", "min_over_time", "max_over_time",
               "count_over_time", "rate"):
        backed = engine.instant(f"{fn}(chip.mxu[5m])", at=at)["result"]
        # Fresh engine over a rule-free clone of the same points: the
        # direct path at the effective window.
        direct = engine.instant(
            f"{fn}(chip.mxu[{eff_w!r}])".replace("[", "[", 1), at=at
        )
        direct_by = {
            tuple(sorted(r["labels"].items())): r["value"]
            for r in direct["result"]
        }
        for r in backed:
            d = direct_by[tuple(sorted(r["labels"].items()))]
            if fn in ("min_over_time", "max_over_time", "count_over_time"):
                assert r["value"] == d, fn
            else:
                assert r["value"] == pytest.approx(d, rel=1e-9), fn


def test_rule_historical_instants_fall_back_to_direct():
    ring, at = _rules_ring()
    engine = QueryEngine(ring)
    # An instant far in the past predates the open bucket: served by
    # the direct path (and must still be correct).
    old = at - 350.0
    out = engine.instant("avg_over_time(chip.mxu[5m])", at=old)["result"]
    assert out and all(r["value"] is not None for r in out)


def test_bad_recording_rule_rejected():
    for text in ("avg(chip.mxu)", "chip.mxu", 'chip.mxu{chip="x"}[5m]', ""):
        with pytest.raises(QueryError):
            RecordingRule(text)


def test_sampler_journals_rejected_rule():
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.config import load_config
    from tpumon.sampler import Sampler

    cfg = load_config(env={
        "TPUMON_COLLECTORS": "accel",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_RECORDING_RULES": "chip.mxu[5m],notaselector(",
    })
    s = Sampler(cfg, accel=FakeTpuCollector(topology="v5e-8"))
    evs = [e for e in s.journal.events() if e["kind"] == "query"]
    assert len(evs) == 1 and evs[0]["severity"] == "serious"
    assert "notaselector" in evs[0]["msg"]
    assert ring_rules_texts(s) == ["chip.mxu[5m]"]  # good rule survives


def ring_rules_texts(sampler):
    return sampler.history.rules.to_json()


# ------------------------------ QSketch --------------------------------


def test_qsketch_exact_under_cap_and_merge_laws():
    import random

    rng = random.Random(7)
    vals = [rng.uniform(0, 100) for _ in range(500)]
    a, b, whole = QSketch(), QSketch(), QSketch()
    for i, v in enumerate(vals):
        whole.add(v)
        (a if i % 2 else b).add(v)
    a.merge(b)
    for q in (0.0, 0.5, 0.95, 1.0):
        assert a.quantile(q) == whole.quantile(q) == _quantile(sorted(vals), q)
    # JSON round trip preserves the answer
    rt = QSketch.from_json(json.loads(json.dumps(a.to_json())))
    assert rt.quantile(0.9) == whole.quantile(0.9)


def test_qsketch_collapse_bounded_error():
    import random

    rng = random.Random(11)
    vals = [rng.lognormvariate(2.0, 1.5) for _ in range(5000)]
    sk = QSketch(cap=256)
    for v in vals:
        sk.add(v)
    assert sk.values is None  # collapsed to buckets
    exact = _quantile(sorted(vals), 0.95)
    approx = sk.quantile(0.95)
    assert approx == pytest.approx(exact, rel=0.45)  # one log-bucket bound
    assert sk.quantile(0.0) >= sk.mn and sk.quantile(1.0) <= sk.mx


# ------------------- distributed algebra (local laws) ------------------


def test_partial_merge_finalize_equals_local_instant():
    ring, flat = load_corpus_ring()
    engine = QueryEngine(ring)
    at = max(pts[-1][0] for pts in flat.values() if pts)
    for src in (
        "sum(chip.mxu)",
        "avg by (host) (chip.mxu)",
        "min(chip.mxu)",
        "max by (host) (chip.mxu)",
        "count(chip.mxu)",
        "topk(3, chip.mxu)",
        "bottomk(2, chip.mxu)",
        "quantile(0.9, chip.mxu)",
    ):
        partial = engine.partial_eval(src, at=at)
        rows = QueryEngine.finalize(
            QueryEngine.merge_partials([partial])
        )
        local = engine.instant(src, at=at)["result"]
        assert rows == local, src


def test_partial_eval_rejects_non_aggregations():
    ring, _ = load_corpus_ring()
    engine = QueryEngine(ring)
    for src in ("chip.mxu", "rate(chip.mxu[1m])", "avg(chip.mxu) + 1"):
        with pytest.raises(QueryError):
            engine.partial_eval(src, at=1.0)


def test_merge_partials_splits_disjoint_and_merges_colliding_groups():
    ring, flat = load_corpus_ring()
    engine = QueryEngine(ring)
    at = max(pts[-1][0] for pts in flat.values() if pts)
    whole = engine.partial_eval("avg(chip.mxu)", at=at)
    # Split the vector in two by excluding halves, as two "leaves".
    names = sorted(n for n in flat if ref_instant(flat[n], at) is not None)
    half = {parse_series_name(n)[1]["chip"] for n in names[: len(names) // 2]}
    p1 = engine.partial_eval(
        "avg(chip.mxu)", at=at,
        exclude=lambda fam, lb: lb.get("chip") in half,
    )
    p2 = engine.partial_eval(
        "avg(chip.mxu)", at=at,
        exclude=lambda fam, lb: lb.get("chip") not in half,
    )
    merged = QueryEngine.merge_partials([p1, p2])
    assert QueryEngine.finalize(merged) == QueryEngine.finalize(whole)


# --------------------------- env expressions ---------------------------


def test_compile_env_alerting_none_semantics():
    f = compile_env("chip.hbm > 50 and chip.mxu < 5")
    assert f({"chip.hbm": 80.0, "chip.mxu": 3.0}) is True
    assert f({"chip.hbm": 80.0, "chip.mxu": 50.0}) is False
    assert f({"chip.hbm": None, "chip.mxu": 3.0}) is False  # no data, no page
    assert f({}) is False
    g = compile_env("chip.link_up == 0 or chip.ici_health == 10")
    assert g({"chip.link_up": 0.0}) is True
    assert g({"chip.link_up": None, "chip.ici_health": 10.0}) is True
    assert g({"chip.link_up": None, "chip.ici_health": None}) is False
    h = compile_env("(host.cpu + 10) / 2")
    assert h({"host.cpu": 90.0}) == 50.0
    assert h({}) is None
    with pytest.raises(QueryError):
        compile_env("avg(chip.mxu)")  # no vector nodes in env exprs
    with pytest.raises(QueryError):
        compile_env("chip.mxu[5m]")


# --------------------------- engine plumbing ---------------------------


def test_eval_condition_matches_generic_truthiness():
    """eval_condition (the SLO engine's short-circuit bad-condition
    path) must agree with bool(eval_compiled(...)) under the
    vector-non-emptiness / scalar-truthiness collapse on every shape —
    including the ones it fast-paths (selector vs constant, both
    orders, negative constants, matchers) and the ones it must NOT
    fast-path (and/or label intersection, vector-vector comparisons,
    arithmetic)."""
    ring = RingHistory(1800)
    at = 1_700_000_000.0
    ring.record("serving.a.ttft_p95_ms", 900.0, ts=at)
    ring.record("serving.b.ttft_p95_ms", 100.0, ts=at)
    ring.record("mxu", 50.0, ts=at)
    ring.record("temp", -5.0, ts=at)
    engine = QueryEngine(ring)
    exprs = [
        "mxu > 10", "mxu > 100", "10 < mxu", "1000 < mxu",
        "mxu == 50", "mxu != 50", "temp < -1", "temp > -1",
        'serving.ttft_p95_ms{tenant="a"} > 800',
        'serving.ttft_p95_ms{tenant="b"} > 800',
        'serving.ttft_p95_ms{tenant="nope"} > 0',
        "absent_series > 0", "absent_series <= 0",
        # fall-through shapes (and/or intersect BY LABELS, not truth)
        'serving.ttft_p95_ms{tenant="a"} > 800 and '
        'serving.ttft_p95_ms{tenant="b"} < 800',
        "mxu > 10 and mxu < 100", "mxu > 100 or mxu < 10",
        "mxu > temp", "mxu - 50", "mxu - 49", "3 > 2", "2 > 3",
    ]
    for src in exprs:
        node = parse(src)
        v = engine.eval_compiled(node, at=at)
        if isinstance(v, list):
            expect = bool(v)
        else:
            expect = bool(v) and v == v and v is not None
        assert engine.eval_condition(node, at=at) is expect, src
    # Shared-context use (the SLO engine's call shape) agrees too.
    ctx = engine.context(at=at)
    assert engine.eval_condition(parse("mxu > 10"), ctx=ctx) is True
    assert engine.eval_condition(parse("mxu > 100"), ctx=ctx) is False


def test_compiled_expression_cache_is_bounded():
    ring = RingHistory(1800)
    engine = QueryEngine(ring)
    for i in range(engine._COMPILE_CAP + 40):
        engine.compile(f"mxu + {i}")
    assert len(engine._compiled) <= engine._COMPILE_CAP


def test_pod_label_via_augmenter():
    ring = RingHistory(1800)
    ring.record("chip.h0/c0.mxu", 10.0, ts=1000.0)
    ring.record("chip.h0/c1.mxu", 20.0, ts=1000.0)

    def augmenter():
        owners = {"h0/c0": "ns/train"}

        def fn(family, labels):
            pod = owners.get(labels.get("chip"))
            if pod:
                labels["pod"] = pod

        return fn

    engine = QueryEngine(ring, augment=augmenter)
    out = engine.instant('chip.mxu{pod="ns/train"}', at=1000.0)["result"]
    assert len(out) == 1 and out[0]["labels"]["pod"] == "ns/train"
    grouped = engine.instant("sum by (pod) (chip.mxu)", at=1000.0)["result"]
    assert {tuple(r["labels"].items()): r["value"] for r in grouped} == {
        (("pod", "ns/train"),): 10.0,
        (): 20.0,
    }


# ------------------------- HTTP routes + CLI ---------------------------


def test_query_routes_and_cli():
    from tests.test_server_api import serve
    from tpumon.query import query_cli

    sampler, server = serve({"TPUMON_RECORDING_RULES": "chip.mxu[5m]"})

    async def scenario():
        for _ in range(3):
            await sampler.tick_fast()
        await server.start()
        port = server.port

        # bare GET: engine info (and the route-liveness contract)
        st, _, body, _ = await server.handle_ex("GET", "/api/query")
        info = json.loads(body)
        assert st == 200 and "rate" in info["functions"]
        assert info["rules"] == ["chip.mxu[5m]"]

        # cached instant + ETag/304
        q = "query=topk(2,avg_over_time(chip.mxu[5m]))"
        st, _, body, hdr = await server.handle_ex("GET", "/api/query", q)
        assert st == 200 and len(json.loads(body)["result"]) == 2
        st2, _, body2, _ = await server.handle_ex(
            "GET", "/api/query", q, if_none_match=hdr["ETag"]
        )
        assert st2 == 304 and body2 == b""

        # range
        st, _, body, _ = await server.handle_ex(
            "GET", "/api/query_range", "query=avg(chip.mxu)&window=5m&step=30s"
        )
        rq = json.loads(body)
        assert st == 200 and rq["series"][0]["points"]

        # 400s: bad expression, bad params, fleet without a hub
        from tpumon.server import HttpError

        for path, params in (
            ("/api/query", "query=rate(("),
            ("/api/query", "query=mxu&time=banana"),
            ("/api/query", "query=mxu&fleet=1"),
            ("/api/query_range", "query=mxu&window=0s"),
            ("/api/query_range", "query=mxu&step=junk"),
        ):
            with pytest.raises(HttpError) as ei:
                await server.handle_ex("GET", path, params)
            assert ei.value.status == 400, (path, params)

        # CLI: instant table, range summary, --json, server-side error
        rc = await asyncio.to_thread(
            query_cli,
            ["avg(chip.mxu)", "--url", f"127.0.0.1:{port}"],
        )
        assert rc == 0
        rc = await asyncio.to_thread(
            query_cli,
            ["chip.mxu", "--url", f"127.0.0.1:{port}",
             "--range", "5m", "--step", "30s", "--json"],
        )
        assert rc == 0
        rc = await asyncio.to_thread(
            query_cli, ["rate((", "--url", f"127.0.0.1:{port}"]
        )
        assert rc == 1
        assert query_cli([]) == 2  # expression required

        await server.stop()

    asyncio.run(scenario())


def test_fleet_query_honors_auth_token():
    """fleet=1 fans sub-queries across the whole tree per request — it
    is gated like /api/profile when a token is configured (local
    cached queries stay open, reference-parity reads)."""
    from tests.test_server_api import serve
    from tpumon.server import HttpError

    sampler, server = serve({"TPUMON_AUTH_TOKEN": "s3cret"})

    async def scenario():
        await sampler.tick_fast()
        with pytest.raises(HttpError) as ei:
            await server.handle_ex("GET", "/api/query", "query=avg(chip.mxu)&fleet=1")
        assert ei.value.status == 401
        # Bearer token passes the gate (then 400s: no hub on a
        # standalone monitor — the auth check comes first).
        with pytest.raises(HttpError) as ei:
            await server.handle_ex(
                "GET", "/api/query", "query=avg(chip.mxu)&fleet=1",
                auth="Bearer s3cret",
            )
        assert ei.value.status == 400
        # Local queries stay open.
        st, _, _, _ = await server.handle_ex(
            "GET", "/api/query", "query=avg(chip.mxu)"
        )
        assert st == 200

    asyncio.run(scenario())


def test_query_cache_key_is_evictable_not_unbounded():
    """Distinct query texts land under the render cache's bounded
    evictable budget — a querying client can't grow the cache without
    limit (same contract as /api/history windows)."""
    from tests.test_server_api import serve

    sampler, server = serve()

    async def scenario():
        await sampler.tick_fast()
        for i in range(40):
            st, _, _, _ = await server.handle_ex(
                "GET", "/api/query", f"query=mxu%20%2B%20{i}"
            )
            assert st == 200
        assert len(server.cache._evictable) <= server.cache.MAX_EVICTABLE

    asyncio.run(scenario())


# ------------------- accelerator-family labels (ISSUE 15) ---------------


def _accel_ring_and_engine():
    """Mixed fleet ring: 3 TPU + 3 GPU chips, one chip.mxu point each,
    plus an augmenter deriving accel from the chip id — the same shape
    the sampler's augmenter produces from live ChipSamples."""
    ring = RingHistory(1800)
    at = 1_700_000_000.0
    vals = {
        "t0/c0": 10.0, "t0/c1": 40.0, "t1/c0": 30.0,
        "g0/gpu-0": 25.0, "g0/gpu-1": 5.0, "g1/gpu-0": 35.0,
    }
    for cid, v in vals.items():
        ring.record(f"chip.{cid}.mxu", v, ts=at)

    def augmenter():
        def fn(family, labels):
            cid = labels.get("chip")
            if cid is not None:
                labels["accel"] = "gpu" if "/gpu-" in cid else "tpu"

        return fn

    return ring, QueryEngine(ring, augment=augmenter), vals, at


def _fam(cid: str) -> str:
    return "gpu" if "/gpu-" in cid else "tpu"


def test_accel_matchers_match_brute_force():
    """{accel="gpu"} matchers and by (accel) group-bys agree with an
    independent brute force over the same values (ISSUE 15 acceptance:
    alert/query/SLO engines all evaluate through this path)."""
    _ring, engine, vals, at = _accel_ring_and_engine()
    gpu_vals = [v for cid, v in vals.items() if _fam(cid) == "gpu"]
    out = engine.instant('avg(chip.mxu{accel="gpu"})', at=at)["result"]
    assert len(out) == 1
    assert out[0]["value"] == pytest.approx(sum(gpu_vals) / len(gpu_vals))
    # != matcher selects the complement.
    out = engine.instant('count(chip.mxu{accel!="gpu"})', at=at)["result"]
    assert out[0]["value"] == 3.0
    grouped = {
        r["labels"]["accel"]: r["value"]
        for r in engine.instant("count(chip.mxu) by (accel)", at=at)["result"]
    }
    assert grouped == {"tpu": 3.0, "gpu": 3.0}
    # Condition path (the alert/SLO engines' entry point) sees them too.
    assert engine.eval_condition(
        parse('chip.mxu{accel="gpu"} > 30'), at=at) is True
    assert engine.eval_condition(
        parse('chip.mxu{accel="gpu"} > 40'), at=at) is False


def test_topk_by_accel_matches_brute_force():
    """Per-group topk (topk(k, v) by (accel)) returns each family's k
    best rows with full labels — checked against a brute force."""
    _ring, engine, vals, at = _accel_ring_and_engine()
    rows = engine.instant("topk(2, chip.mxu) by (accel)", at=at)["result"]
    got: dict[str, list[float]] = {}
    for r in rows:
        assert r["labels"]["chip"]  # full labels survive the ranking
        got.setdefault(r["labels"]["accel"], []).append(r["value"])
    brute: dict[str, list[float]] = {}
    for cid, v in vals.items():
        brute.setdefault(_fam(cid), []).append(v)
    assert set(got) == {"tpu", "gpu"}
    for fam, xs in brute.items():
        assert sorted(got[fam], reverse=True) == sorted(xs, reverse=True)[:2]
    # Ungrouped topk is unchanged by the grouping support.
    flat = engine.instant("topk(2, chip.mxu)", at=at)["result"]
    assert [r["value"] for r in flat] == sorted(vals.values(), reverse=True)[:2]


def test_topk_by_partial_merge_equals_local():
    """The distributed algebra for grouped topk: splitting the chips
    across two 'leaves' and merging their partials equals the local
    answer — per-group k-sets are locally complete, so any merge order
    is correct (the fleet `by (accel)` query's correctness claim)."""
    _ring, engine, vals, at = _accel_ring_and_engine()
    for expr in (
        "topk(2, chip.mxu) by (accel)",
        "bottomk(1, chip.mxu) by (accel)",
        "topk(2, chip.mxu)",
    ):
        names = sorted(vals)
        half = set(names[: len(names) // 2])
        p1 = engine.partial_eval(
            expr, at=at, exclude=lambda f, lb: lb.get("chip") in half)
        p2 = engine.partial_eval(
            expr, at=at, exclude=lambda f, lb: lb.get("chip") not in half)
        merged = QueryEngine.finalize(QueryEngine.merge_partials([p1, p2]))
        local = engine.instant(expr, at=at)["result"]
        key = lambda r: (tuple(sorted(r["labels"].items())), r["value"])
        assert sorted(merged, key=key) == sorted(local, key=key), expr
