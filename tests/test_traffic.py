"""Multi-tenant traffic simulator (tpumon.loadgen.traffic): seeded
replay, scenario shapes, the diurnal rate profile, and the
scheduler-degradation knob. The sim is duck-typed over the engine, so
everything here runs against a recording stub — no model, no jax
compile; the real-engine integration lives in tests/test_slo.py
(tenant propagation) and tests/test_slo_soak.py (the closed loop)."""

import threading
import time
from types import SimpleNamespace

import pytest

from tpumon.loadgen.traffic import TenantSpec, TrafficSim


class StubEngine:
    """Records submissions; never holds work (step() -> False)."""

    def __init__(self, vocab=512, prefill_len=16):
        self.cfg = SimpleNamespace(
            model=SimpleNamespace(vocab=vocab), prefill_len=prefill_len)
        self.max_queue = 64
        self.submitted: list[tuple] = []
        self.steps = 0

    def submit(self, prompt, max_new=16, temperature=0.0, top_k=0,
               tenant=""):
        self.submitted.append(
            (tenant, tuple(prompt), max_new, temperature))
        return SimpleNamespace(tenant=tenant, prompt=list(prompt))

    def step(self):
        self.steps += 1
        return False

    def stream(self, tenant):
        return [s for s in self.submitted if s[0] == tenant]


def mk_sim(tenants, seed=42, engine=None, **kw):
    return TrafficSim(engine or StubEngine(), tenants, seed=seed, **kw)


CHAT = TenantSpec(name="chat", scenario="chat", rps=5.0)
RAG = TenantSpec(name="rag", scenario="rag", rps=1.0, prompt_chunks=4)
BATCH = TenantSpec(name="batch", scenario="batch", rps=0.5)


def test_seeded_runs_replay_identically():
    a, b = mk_sim([CHAT, RAG, BATCH]), mk_sim([CHAT, RAG, BATCH])
    for sim in (a, b):
        for _ in range(25):
            sim.fire("chat")
            sim.fire("rag")
            sim.fire("batch")
    assert a.engine.submitted == b.engine.submitted
    # A different seed produces a different stream (the RNG is real).
    c = mk_sim([CHAT, RAG, BATCH], seed=7)
    for _ in range(25):
        c.fire("chat")
    assert c.engine.stream("chat") != a.engine.stream("chat")[:25]


def test_adding_a_tenant_never_perturbs_another():
    """Per-tenant RNGs are seeded by (seed, name): the chat stream is
    identical whether or not batch traffic exists alongside it."""
    alone = mk_sim([CHAT])
    mixed = mk_sim([CHAT, BATCH])
    for _ in range(20):
        alone.fire("chat")
        mixed.fire("chat")
        mixed.fire("batch")
    assert alone.engine.stream("chat") == mixed.engine.stream("chat")


def test_scenario_shapes():
    sim = mk_sim([CHAT, RAG, BATCH])
    p = sim.engine.cfg.prefill_len
    for _ in range(10):
        sim.fire("chat")
        sim.fire("rag")
        sim.fire("batch")
    chat = sim.engine.stream("chat")
    rag = sim.engine.stream("rag")
    batch = sim.engine.stream("batch")
    # chat: short prompts (within one chunk), sampled, latency-shaped.
    assert all(2 <= len(s[1]) <= p for s in chat)
    assert all(s[2] == 16 and s[3] == pytest.approx(0.7) for s in chat)
    # rag: long prompts behind a shared per-tenant prefix — every
    # request's first (chunks-1)*p tokens are identical (the prefix
    # cache's hit case), with a per-request tail.
    shared_len = (4 - 1) * p
    assert all(len(s[1]) > shared_len for s in rag)
    prefixes = {s[1][:shared_len] for s in rag}
    assert len(prefixes) == 1
    tails = {s[1][shared_len:] for s in rag}
    assert len(tails) > 1
    # batch: offline bulk — big max_new, greedy.
    assert all(s[2] == 64 and s[3] == 0.0 for s in batch)


def test_diurnal_rate_profile_is_deterministic():
    spec = TenantSpec(name="t", rps=2.0, diurnal_amp=0.5,
                      diurnal_period_s=100.0)
    sim = mk_sim([spec])
    rate = sim._rate_fn(spec)
    assert rate(0.0) == pytest.approx(2.0)
    assert rate(25.0) == pytest.approx(3.0)   # peak: rps * (1 + amp)
    assert rate(75.0) == pytest.approx(1.0)   # trough
    # Full-swing amp clamps at zero rather than going negative.
    deep = TenantSpec(name="d", rps=2.0, diurnal_amp=1.5,
                      diurnal_period_s=100.0)
    assert mk_sim([deep])._rate_fn(deep)(75.0) == 0.0
    # time_scale compresses sim time: scale 100 reaches the peak at
    # wall t=0.25.
    scaled = mk_sim([spec], time_scale=100.0)
    assert scaled._rate_fn(spec)(0.25) == pytest.approx(3.0)


def test_degradation_knob_stalls_steps_and_releases():
    sim = mk_sim([CHAT])
    t0 = time.monotonic()
    sim._step()
    assert time.monotonic() - t0 < 0.05
    sim.degrade(0.05)
    assert sim.degraded
    t0 = time.monotonic()
    sim._step()
    assert time.monotonic() - t0 >= 0.05
    sim.degrade(0)
    assert not sim.degraded
    assert sim.engine.steps == 2
    # The knob clamps at SET time, so the reported state is the
    # effective fault (not a silently-milder one).
    sim.degrade(5.0)
    assert sim._stall_s == TrafficSim.MAX_STALL_S
    assert sim.to_json()["stall_s"] == TrafficSim.MAX_STALL_S


def test_pump_drives_seeded_arrivals_live():
    """End to end over the shared ArrivalPump: a hot tenant submits at
    roughly its rate, a zero-rate tenant never fires, and stop joins
    the thread."""
    hot = TenantSpec(name="hot", rps=200.0)
    cold = TenantSpec(name="cold", rps=0.0)
    sim = mk_sim([hot, cold])
    sim.start()
    deadline = time.monotonic() + 5.0
    while (not sim.engine.stream("hot")) and time.monotonic() < deadline:
        time.sleep(0.01)
    sim.stop()
    assert sim._thread is None
    assert len(sim.engine.stream("hot")) >= 1
    assert sim.engine.stream("cold") == []
    assert all(s[0] == "hot" for s in sim.engine.submitted)
    j = sim.to_json()
    assert j["tenants"]["hot"]["submitted"] == len(sim.engine.stream("hot"))


def test_validation():
    with pytest.raises(ValueError, match="at least one"):
        mk_sim([])
    with pytest.raises(ValueError, match="duplicate"):
        mk_sim([CHAT, TenantSpec(name="chat")])
    with pytest.raises(ValueError, match="unknown scenario"):
        mk_sim([TenantSpec(name="x", scenario="video")])
    # Dot-free by the series-naming contract: a dotted tenant would
    # mis-split serving.<tenant>.<metric> and its SLOs could silently
    # never fire.
    with pytest.raises(ValueError, match="dot-free"):
        mk_sim([TenantSpec(name="team.a")])
    with pytest.raises(ValueError, match="dot-free"):
        mk_sim([TenantSpec(name="")])


def test_paused_source_produces_no_catch_up_burst():
    """A source whose rate() is 0 for a span must yield ZERO arrivals
    for it — not a thundering herd on resume. The pump re-anchors a
    paused source's clock, so only post-resume time generates load."""
    from tpumon.loadgen.serving import ArrivalPump, ArrivalSource

    engine = StubEngine()
    fired = []
    resume_at = time.monotonic() + 0.4
    src = ArrivalSource(
        # paused for the first ~0.4s, then 50 rps (deterministic
        # 20 ms gaps, so any same-instant cluster IS the bug, not
        # Poisson clustering)
        rate=lambda rel: 0.0 if time.monotonic() < resume_at else 50.0,
        fire=lambda rel: fired.append(time.monotonic()),
        interval=lambda rate: 1.0 / rate,
    )
    stop = threading.Event()
    ArrivalPump(engine, [src]).run(stop, duration=0.6)
    assert fired, "source never resumed"
    # No catch-up burst covering the 0.4 s pause (~20 arrivals): the
    # resume fires one immediate arrival, then 20 ms-spaced ones.
    burst = [t for t in fired if t - fired[0] < 0.01]
    assert len(burst) <= 2, f"{len(burst)} arrivals fired as a resume burst"
    assert fired[0] >= resume_at


def test_stop_is_idempotent_and_threadsafe():
    sim = mk_sim([CHAT])
    sim.start()
    sim.stop()
    sim.stop()  # second stop is a no-op, not an error
    assert not any(
        t.name.startswith("Thread-") and t is sim._thread
        for t in threading.enumerate()
    )
