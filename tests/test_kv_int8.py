"""int8 KV cache (ServeConfig.kv_dtype='int8').

Quantized K/V rows halve resident cache HBM and the bytes decode
attention streams; outputs drift only by quantization noise, so greedy
token streams should overwhelmingly agree with the bf16-cache engine.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import (
    ServeConfig,
    ServingEngine,
    _kv_dequant,
    _kv_quant,
    init_cache,
)

MODEL = ModelConfig(vocab=512, d_model=128, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=256, max_seq=128)
PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7], [2, 7, 1, 8]]


def test_quant_roundtrip_accuracy():
    import jax

    x = jax.random.normal(jax.random.PRNGKey(0), (16, 2, 64), jnp.float32)
    q, s = _kv_quant(x)
    assert q.dtype == jnp.int8 and s.shape == (16, 2)
    back = _kv_dequant(q, s, jnp.float32)
    # Symmetric per-row int8: worst-case error is scale/2 = max|x|/254.
    err = jnp.max(jnp.abs(back - x))
    assert float(err) <= float(jnp.max(jnp.abs(x))) / 254 + 1e-6
    # All-zero rows (fresh cache) stay exactly zero.
    zq, zs = _kv_quant(jnp.zeros((4, 2, 64)))
    assert float(jnp.max(jnp.abs(_kv_dequant(zq, zs, jnp.float32)))) == 0.0


def test_int8_cache_layout_and_size():
    cfg = ServeConfig(model=MODEL, slots=2, prefill_len=16, kv_dtype="int8")
    cache = init_cache(cfg)
    assert cache["k"].dtype == jnp.int8 and cache["v"].dtype == jnp.int8
    assert cache["ks"].shape == cache["k"].shape[:-1]
    bf16 = init_cache(ServeConfig(model=MODEL, slots=2, prefill_len=16))
    int8_bytes = sum(a.size * a.dtype.itemsize for a in cache.values())
    bf16_bytes = sum(a.size * a.dtype.itemsize for a in bf16.values())
    # ~2x smaller net of the f32 scales (exact at hd=32: 1/2 + 4/32... )
    assert int8_bytes < bf16_bytes * 0.6


def run(cfg_kw, quantize=None, max_new=12):
    eng = ServingEngine(cfg=ServeConfig(
        model=MODEL, slots=2, prefill_len=16, **cfg_kw), quantize=quantize)
    reqs = [eng.submit(p, max_new=max_new) for p in PROMPTS]
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    return [r.output for r in reqs]


def test_int8_kv_logits_near_bf16_cache():
    """Quantization error bound at the logits level: prefill + a few
    decode steps through the int8 cache must track the bf16-cache
    logits closely. (Token streams aren't compared: an untrained
    random-init model has argmax near-ties everywhere, so any noise
    eventually forks a stream — that says nothing about cache
    fidelity.)"""
    import dataclasses
    from functools import partial

    import jax

    from tpumon.loadgen.model import init_params
    from tpumon.loadgen.serving import decode_step, prefill

    model = dataclasses.replace(MODEL, compute_dtype="float32")
    cfg = ServeConfig(model=model, slots=2, prefill_len=16)
    qcfg = dataclasses.replace(cfg, kv_dtype="int8")
    params = init_params(model, jax.random.PRNGKey(0))
    toks = jnp.asarray([3, 1, 4, 1, 5] + [0] * 11, jnp.int32)

    def run_path(c, feed=None):
        """feed: fixed token sequence (so both paths see identical
        inputs and only the cache representation differs); None = argmax."""
        cache = init_cache(c)
        cache, logits = jax.jit(partial(prefill, c))(
            params, cache, toks, jnp.int32(5), jnp.int32(0), jnp.int32(0))
        outs = [logits]
        fed = []
        pos = jnp.asarray([5, 0], jnp.int32)
        for i in range(4):
            tok = int(feed[i]) if feed else int(jnp.argmax(outs[-1]))
            fed.append(tok)
            last = jnp.asarray([tok, tok], jnp.int32)
            cache, logits = jax.jit(partial(decode_step, c))(
                params, cache, last, pos)
            outs.append(logits[0])
            pos = pos + 1
        return outs, fed

    ref, fed = run_path(cfg)
    quant, _ = run_path(qcfg, feed=fed)
    for a, b in zip(ref, quant):
        scale = float(jnp.max(jnp.abs(a))) or 1.0
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 0.05, rel  # int8 per-row quantization noise bound


def test_int8_kv_streams_run_to_completion():
    outs = run({"kv_dtype": "int8"})
    assert all(len(o) == 13 for o in outs)  # prefill token + 12 decoded


def test_int8_kv_composes_with_block_decode_and_int8_weights():
    base = run({"kv_dtype": "int8"}, quantize="int8")
    fused = run({"kv_dtype": "int8", "decode_block": 4}, quantize="int8")
    # Same numerics, same schedule -> identical.
    assert base == fused


def test_int8_kv_paged_pool():
    """int8 KV over the paged pool: same quantization scheme page-wise;
    fused block decode composes; output matches per-step paged int8."""
    base = run({"kv_dtype": "int8", "kv_layout": "paged", "pool_pages": 9})
    fused = run({"kv_dtype": "int8", "kv_layout": "paged", "pool_pages": 9,
                 "decode_block": 4})
    assert base == fused
    assert all(len(o) == 13 for o in base)
    # Pool halves too (net of scales).
    from tpumon.loadgen.paged_kv import init_pool

    qp = init_pool(ServeConfig(model=MODEL, prefill_len=16,
                               kv_dtype="int8"), 8)
    bp = init_pool(ServeConfig(model=MODEL, prefill_len=16), 8)
    assert qp["k"].dtype == jnp.int8
    qb = sum(a.size * a.dtype.itemsize for a in qp.values())
    bb = sum(a.size * a.dtype.itemsize for a in bp.values())
    assert qb < bb * 0.6


def test_int8_kv_invalid_compositions():
    for kw in ({"spec_len": 2},
               {"prefix_cache_entries": 4}):
        with pytest.raises(ValueError, match="int8"):
            ServingEngine(cfg=ServeConfig(
                model=MODEL, prefill_len=16, kv_dtype="int8", **kw))
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(cfg=ServeConfig(model=MODEL, kv_dtype="fp8"))
