"""Columnar time-series core (tpumon.tsdb): chunk-codec round-trips
over adversarial streams (ISSUE 5 satellite), tier retention/query
semantics, and the v2 binary snapshot codec's refuse-on-corruption
guarantees."""

import json
import math
import os
import random
import struct

import pytest

from tpumon import tsdb

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def f32(v: float) -> float:
    """The store's value dtype: float32 quantization."""
    return struct.unpack("<f", struct.pack("<f", v))[0]


def assert_roundtrip(ts_ms, values):
    bits = [tsdb.f32bits(v) for v in values]
    blob = tsdb.encode_chunk(list(ts_ms), bits)
    ts2, bits2 = tsdb.decode_chunk(blob)
    assert ts2 == list(ts_ms)
    # Bit-exact: stronger than float32 tolerance, and the only
    # comparison that works for NaN payloads.
    assert bits2 == bits
    return blob


# ------------------------- codec round-trips ---------------------------


def test_constant_stream_compresses_to_about_two_bytes_per_point():
    ts = [1_700_000_000_000 + i * 1000 for i in range(1000)]
    blob = assert_roundtrip(ts, [73.25] * 1000)
    assert len(blob) / 1000 < 2.5  # dod=0 (1B) + xor=0 (1B) steady state


def test_random_streams_roundtrip_property():
    rng = random.Random(20250803)
    for _ in range(50):
        n = rng.randint(1, 400)
        t = rng.randint(0, 2**41)
        ts, vals = [], []
        for _ in range(n):
            t += rng.choice([0, 1, 997, 1000, 1003, 60_000, -500])
            ts.append(t)
            vals.append(
                rng.choice(
                    [0.0, 1.0, -1.0, rng.uniform(-1e9, 1e9), rng.uniform(-1, 1)]
                )
            )
        assert_roundtrip(ts, vals)


def test_nan_inf_and_signed_zero_roundtrip():
    vals = [0.0, -0.0, float("nan"), float("inf"), float("-inf"), 1e-40, 3.4e38]
    ts = [i * 1000 for i in range(len(vals))]
    bits = [tsdb.f32bits(v) for v in vals]
    _, bits2 = tsdb.decode_chunk(tsdb.encode_chunk(ts, bits))
    out = [tsdb.bits_to_f32(b) for b in bits2]
    assert math.isnan(out[2]) and out[3] == math.inf and out[4] == -math.inf
    assert struct.pack("<f", out[1]) == struct.pack("<f", -0.0)  # -0.0 kept


def test_monotonic_reversed_and_duplicate_ts_roundtrip():
    up = list(range(0, 300_000, 1000))
    assert_roundtrip(up, [float(i) for i in range(300)])
    assert_roundtrip(list(reversed(up)), [float(i) for i in range(300)])
    assert_roundtrip([7_000] * 300, [0.5] * 300)


def test_fuzz_seed_corpus_roundtrips():
    """The checked-in adversarial corpus (tests/fixtures/tsdb_fuzz.json):
    every stream must encode→decode bit-identically."""
    with open(os.path.join(FIXTURES, "tsdb_fuzz.json")) as f:
        corpus = json.load(f)
    assert len(corpus) >= 8
    for stream in corpus:
        vals = [float(v) for v in stream["values"]]  # "nan"/"inf" markers
        assert_roundtrip(stream["ts_ms"], vals)


def test_truncated_chunk_raises_not_garbage():
    ts = [i * 1000 for i in range(100)]
    blob = tsdb.encode_chunk(ts, [tsdb.f32bits(float(i)) for i in range(100)])
    for cut in range(len(blob) - 1):
        with pytest.raises(ValueError):
            tsdb.decode_chunk(blob[:cut])


# ------------------------------ tiers ----------------------------------


def test_tier_seal_and_query_across_chunks():
    tier = tsdb.Tier(window_s=1e9, seal_points=32)
    for i in range(100):
        tier.append(float(i), f32(i * 0.5))
    assert len(tier.chunks) == 3 and len(tier.head_ts) == 4
    assert len(tier) == 100
    pts = tier.since(40.0)
    assert [t for t, _ in pts] == [float(i) for i in range(40, 100)]
    assert pts[0][1] == f32(20.0)
    assert tier.first() == (0.0, 0.0) and tier.last() == (99.0, f32(49.5))


def test_tier_eviction_masks_partially_expired_chunk():
    tier = tsdb.Tier(window_s=50.0, seal_points=32)
    for i in range(100):
        tier.append(float(i), 1.0)
    # Whole chunks older than the window dropped; the seam chunk stays
    # resident but its expired points never surface.
    assert tier.first()[0] >= 99 - 50
    assert len(tier) == 51
    assert all(t >= 49.0 for t, _ in tier.since(None))


def test_tier_out_of_order_insert_keeps_sorted_order():
    tier = tsdb.Tier(window_s=1e9, seal_points=16)
    for i in range(40):
        tier.append(1000.0 + i, float(i))
    tier.append(500.0, 7.0)  # restore-path style late point
    pts = tier.since(None)
    assert [t for t, _ in pts] == sorted(t for t, _ in pts)
    assert pts[0] == (500.0, 7.0)
    # Ring still appends normally afterwards.
    tier.append(2000.0, 9.0)
    assert tier.last() == (2000.0, 9.0)


def test_points_view_sequence_protocol():
    tier = tsdb.Tier(window_s=1e9, seal_points=8)
    writes = []
    view = tsdb.PointsView(tier, on_write=lambda: writes.append(1))
    assert not view and len(view) == 0
    view.extend([(float(i), float(i * 2)) for i in range(20)])
    assert len(writes) == 20
    assert view and len(view) == 20
    assert view[0] == (0.0, 0.0) and view[-1] == (19.0, 38.0)
    assert view[3] == (3.0, 6.0)
    assert list(view) == list(reversed(list(reversed(view))))
    with pytest.raises(IndexError):
        view[99]


def test_resident_bytes_vastly_under_tuple_deque():
    """The tentpole's memory claim at unit scale: a sealed columnar
    series resides in a small fraction of the tuple-deque bytes."""
    import sys
    from collections import deque

    tier = tsdb.Tier(window_s=1e9, seal_points=256)
    dq = deque()
    for i in range(5000):
        ts, v = 1_700_000_000.0 + i, 50.0 + (i % 7)
        tier.append(ts, f32(v))
        dq.append((ts, v))
    deque_bytes = sum(
        sys.getsizeof(p) + sys.getsizeof(p[0]) + sys.getsizeof(p[1]) for p in dq
    ) + sys.getsizeof(dq)
    assert tier.resident_bytes() * 4 < deque_bytes


# ----------------------- binary snapshot codec -------------------------


class _Series:
    """Duck-typed series (fine + down) as dump_snapshot expects."""

    def __init__(self):
        self.fine = tsdb.Tier(window_s=1e9, seal_points=16)
        self.down = [tsdb.Downsample(60.0, 1e9)]


def _make_series(n=50):
    s = _Series()
    for i in range(n):
        ts, v = 1000.0 + i, f32(10.0 + i * 0.5)
        s.fine.append(ts, v)
        s.down[0].observe(ts, v)
    return s


def test_snapshot_roundtrip_chunks_verbatim():
    s = _make_series()
    blob = tsdb.dump_snapshot({"cpu": s}, saved_at=123.0)
    saved_at, dumps = tsdb.load_snapshot(blob)
    assert saved_at == 123.0 and len(dumps) == 1
    d = dumps[0]
    assert d["name"] == "cpu"
    # Chunk bytes round-trip verbatim — no re-encode on either side.
    assert [c.data for c in d["fine"]["chunks"]] == [
        c.data for c in s.fine.chunks
    ]
    assert list(d["fine"]["head_ts"]) == list(s.fine.head_ts)
    # The live downsample bucket's accumulator survives.
    assert d["down"][0]["bn"] == s.down[0].bn
    assert tsdb.tier_points(d["fine"]) == s.fine.since(None)


def test_snapshot_refuses_truncation_everywhere():
    blob = tsdb.dump_snapshot({"cpu": _make_series(), "mxu": _make_series()}, 1.0)
    # Every proper prefix must raise ValueError — never return garbage,
    # never throw anything a caller wouldn't catch.
    for cut in range(len(blob)):
        with pytest.raises(ValueError):
            tsdb.load_snapshot(blob[:cut])


def test_snapshot_refuses_bad_magic_and_corrupt_index():
    blob = tsdb.dump_snapshot({"cpu": _make_series()}, 1.0)
    with pytest.raises(ValueError):
        tsdb.load_snapshot(b"NOTHIST!" + blob[8:])
    # Flip a byte inside the JSON index.
    mangled = bytearray(blob)
    mangled[len(tsdb.MAGIC) + 4 + 2] = 0xFF
    with pytest.raises(ValueError):
        tsdb.load_snapshot(bytes(mangled))
