"""Prefix caching tests (tpumon.loadgen.prefix_cache).

The load-bearing invariant: a cache hit restores bit-identical K/V, so
greedy outputs never change — only prefill work does.
"""

import dataclasses

import jax
import jax.numpy as jnp

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.prefix_cache import PrefixCache
from tpumon.loadgen.serving import ServeConfig, ServingEngine

SMALL = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=64,
                    compute_dtype="float32")


def make_engine(entries=4, **kw):
    return ServingEngine(cfg=ServeConfig(
        model=SMALL, slots=2, prefill_len=8,
        prefix_cache_entries=entries, **kw))


SYS = [7, 1, 8, 2, 8, 1, 8, 2]  # exactly one chunk (prefill_len=8)
PROMPT_A = SYS + [3, 1, 4, 1, 5]
PROMPT_B = SYS + [9, 2, 6, 5]


class TestPrefixCacheUnit:
    def test_strict_prefix_only(self):
        pc = PrefixCache(chunk=8)
        # A chunk-aligned prompt must never be served entirely from
        # cache: the final chunk is recomputed for first-token logits.
        assert pc.cached_prefix_len(list(range(8))) == 0  # m would be n
        assert pc.cached_prefix_len(list(range(5))) == 0

    def test_lru_eviction_bounds_entries(self):
        eng = make_engine(entries=2)
        for i in range(5):
            eng.submit(SYS[:-1] + [i] + [i, i + 1], max_new=1)
            eng.drain()
        assert eng.prefix_cache.entries <= 2
        # Incremental byte accounting survives evictions: 2 entries of
        # one 8-row chunk each, k+v, f32.
        m = SMALL
        per_entry = 2 * m.n_layers * 8 * m.n_kv_heads * m.head_dim * 4
        assert eng.prefix_cache.resident_bytes() == 2 * per_entry


class TestPrefixCacheEngine:
    def test_hit_outputs_match_cold_outputs(self):
        cold = make_engine(entries=0)
        r1 = cold.submit(PROMPT_A, max_new=10)
        cold.drain()

        warm = make_engine(entries=4)
        w1 = warm.submit(PROMPT_A, max_new=10)
        warm.drain()
        assert warm.prefix_cache.hits == 0  # first sight: miss
        w2 = warm.submit(PROMPT_A, max_new=10)
        warm.drain()
        assert warm.prefix_cache.hits == 1
        assert warm.prefix_cache.saved_tokens == 8
        # Restored K/V is bit-identical, so all three greedy outputs
        # agree (cold, warm-miss, warm-hit).
        assert r1.output == w1.output == w2.output

    def test_shared_prefix_across_different_tails(self):
        eng = make_engine(entries=4)
        eng.submit(PROMPT_A, max_new=6)
        eng.drain()
        rb = eng.submit(PROMPT_B, max_new=6)
        eng.drain()
        assert eng.prefix_cache.hits == 1  # B reuses A's SYS chunk

        cold = make_engine(entries=0)
        rb_cold = cold.submit(PROMPT_B, max_new=6)
        cold.drain()
        assert rb.output == rb_cold.output

    def test_composes_with_speculative_decoding(self):
        plain = make_engine(entries=0)
        r0 = plain.submit(PROMPT_A, max_new=10)
        plain.drain()

        eng = make_engine(entries=4, spec_len=3)
        eng.submit(PROMPT_A, max_new=10)
        eng.drain()
        r2 = eng.submit(PROMPT_A, max_new=10)
        eng.drain()
        assert eng.prefix_cache.hits == 1
        assert eng.spec_rounds_total > 0
        # Draft cache is prefilled fully (prefix cache holds target K/V
        # only), so self-speculation still accepts everything.
        assert eng.spec_accepted_total == eng.spec_proposed_total
        assert r2.output == r0.output

    def test_metrics_exported(self):
        eng = make_engine(entries=4)
        eng.submit(PROMPT_A, max_new=2)
        eng.drain()
        eng.submit(PROMPT_A, max_new=2)
        eng.drain()
        text = eng.metrics_text()
        assert "tpumon_serving_prefix_hits 1" in text
        assert "tpumon_serving_prefix_saved_tokens 8" in text
        assert "tpumon_serving_prefix_bytes" in text
        # Disabled engine exports no prefix families at all.
        off = make_engine(entries=0)
        assert "prefix_hits" not in off.metrics_text()
