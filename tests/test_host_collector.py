"""Golden-input tests for the host collector (SURVEY §4.1: each collector
is a thin parser over an external format; shapes from monitor_server.js:68-79)."""

import asyncio

import pytest

from tpumon.collectors.host import (
    HostCollector,
    _read_proc_stat_cpu,
    parse_meminfo,
    parse_net_dev,
)

MEMINFO = """\
MemTotal:       16384000 kB
MemFree:         2048000 kB
MemAvailable:    8192000 kB
Buffers:          512000 kB
Cached:          4096000 kB
"""

LOADAVG = "2.45 1.80 1.20 3/1234 56789\n"

STAT_T0 = "cpu  1000 50 500 8000 200 0 50 0 0 0\ncpu0 500 25 250 4000 100 0 25 0 0 0\n"
# +300 busy (user+system), +700 total
STAT_T1 = "cpu  1250 50 550 8400 200 0 50 0 0 0\ncpu0 625 25 275 4200 100 0 25 0 0 0\n"

NET_DEV_T0 = """\
Inter-|   Receive                                                |  Transmit
 face |bytes    packets errs drop fifo frame compressed multicast|bytes    packets errs drop fifo colls carrier compressed
    lo: 9999999    9999    0    0    0     0          0         0  9999999    9999    0    0    0     0       0          0
  eth0: 1000000    5000    0    0    0     0          0         0  2000000    4000    0    0    0     0       0          0
  ens5:  500000    2500    0    0    0     0          0         0   300000    1500    0    0    0     0       0          0
"""
NET_DEV_T1 = NET_DEV_T0.replace("1000000", "1600000").replace("2000000", "3200000")


def make_proc(tmp_path, stat=STAT_T0):
    (tmp_path / "meminfo").write_text(MEMINFO)
    (tmp_path / "loadavg").write_text(LOADAVG)
    (tmp_path / "stat").write_text(stat)
    (tmp_path / "net").mkdir(exist_ok=True)
    (tmp_path / "net" / "dev").write_text(NET_DEV_T0)
    return str(tmp_path)


def test_parse_meminfo_units():
    mi = parse_meminfo(MEMINFO)
    assert mi["MemTotal"] == 16384000 * 1024
    assert mi["MemAvailable"] == 8192000 * 1024


def test_proc_stat_cpu_line():
    busy, total = _read_proc_stat_cpu(STAT_T0)
    assert total == 1000 + 50 + 500 + 8000 + 200 + 0 + 50 + 0
    assert busy == total - 8000 - 200


def test_host_collect_golden(tmp_path):
    c = HostCollector(cpu_count=8, proc_root=make_proc(tmp_path))
    s = asyncio.run(c.collect())
    assert s.ok
    # First sample: load-based estimate (reference formula with real cores,
    # monitor_server.js:76).
    assert s.data["cpu"]["load_1min"] == 2.45
    assert s.data["cpu"]["percent"] == pytest.approx(100 * 2.45 / 8, abs=0.1)
    mem = s.data["memory"]
    assert mem["total"] == 16384000 * 1024
    assert mem["percent"] == pytest.approx(50.0, abs=0.1)
    disk = s.data["disk"]
    assert disk["total"] > 0 and 0 <= disk["percent"] <= 100


def test_host_cpu_percent_from_stat_delta(tmp_path):
    proc = make_proc(tmp_path)
    c = HostCollector(cpu_count=8, proc_root=proc)
    asyncio.run(c.collect())
    (tmp_path / "stat").write_text(STAT_T1)
    s = asyncio.run(c.collect())
    # busy delta = 300, total delta = 700
    assert s.data["cpu"]["percent"] == pytest.approx(100 * 300 / 700, abs=0.1)


def test_parse_net_dev_excludes_loopback():
    out = parse_net_dev(NET_DEV_T0)
    assert "lo" not in out
    assert out["eth0"] == (1000000, 2000000)
    assert out["ens5"] == (500000, 300000)


def test_host_collect_net_counters(tmp_path):
    c = HostCollector(cpu_count=8, proc_root=make_proc(tmp_path))
    s = asyncio.run(c.collect())
    assert s.ok
    net = s.data["net"]
    assert net["rx_bytes"] == 1500000 and net["tx_bytes"] == 2300000
    assert net["interfaces"]["eth0"]["tx_bytes"] == 2000000


def test_sampler_net_rates_as_dcn_series(tmp_path):
    """NIC byte deltas become the DCN-traffic proxy rate + history
    series (SURVEY §5.8: ICI within a slice, DCN across hosts)."""
    from tpumon.config import load_config
    from tpumon.sampler import Sampler

    proc = make_proc(tmp_path)
    c = HostCollector(cpu_count=8, proc_root=proc)
    cfg = load_config(env={"TPUMON_COLLECTORS": "host"})
    sampler = Sampler(cfg, host=c)
    asyncio.run(sampler.tick_fast())
    (tmp_path / "net" / "dev").write_text(NET_DEV_T1)
    asyncio.run(sampler.tick_fast())
    assert sampler.net_rates["rx_bps"] > 0
    assert sampler.net_rates["tx_bps"] > sampler.net_rates["rx_bps"]
    assert sampler.history.series["dcn"].points


def test_host_degrades_per_subsource(tmp_path):
    """Reference contract: errors degrade to empty objects, not a crash
    (monitor_server.js:80) — but tpumon records the error."""
    (tmp_path / "loadavg").write_text(LOADAVG)
    (tmp_path / "stat").write_text(STAT_T0)
    (tmp_path / "net").mkdir(exist_ok=True)
    (tmp_path / "net" / "dev").write_text(NET_DEV_T0)
    # no meminfo file
    c = HostCollector(cpu_count=8, proc_root=str(tmp_path))
    s = asyncio.run(c.collect())
    assert not s.ok
    assert s.data["memory"] == {}
    assert s.data["cpu"]["load_1min"] == 2.45  # other sub-sources still work
    assert "memory" in s.error
