"""Golden-input tests for the host collector (SURVEY §4.1: each collector
is a thin parser over an external format; shapes from monitor_server.js:68-79)."""

import asyncio

import pytest

from tpumon.collectors.host import HostCollector, parse_meminfo, _read_proc_stat_cpu

MEMINFO = """\
MemTotal:       16384000 kB
MemFree:         2048000 kB
MemAvailable:    8192000 kB
Buffers:          512000 kB
Cached:          4096000 kB
"""

LOADAVG = "2.45 1.80 1.20 3/1234 56789\n"

STAT_T0 = "cpu  1000 50 500 8000 200 0 50 0 0 0\ncpu0 500 25 250 4000 100 0 25 0 0 0\n"
# +300 busy (user+system), +700 total
STAT_T1 = "cpu  1250 50 550 8400 200 0 50 0 0 0\ncpu0 625 25 275 4200 100 0 25 0 0 0\n"


def make_proc(tmp_path, stat=STAT_T0):
    (tmp_path / "meminfo").write_text(MEMINFO)
    (tmp_path / "loadavg").write_text(LOADAVG)
    (tmp_path / "stat").write_text(stat)
    return str(tmp_path)


def test_parse_meminfo_units():
    mi = parse_meminfo(MEMINFO)
    assert mi["MemTotal"] == 16384000 * 1024
    assert mi["MemAvailable"] == 8192000 * 1024


def test_proc_stat_cpu_line():
    busy, total = _read_proc_stat_cpu(STAT_T0)
    assert total == 1000 + 50 + 500 + 8000 + 200 + 0 + 50 + 0
    assert busy == total - 8000 - 200


def test_host_collect_golden(tmp_path):
    c = HostCollector(cpu_count=8, proc_root=make_proc(tmp_path))
    s = asyncio.run(c.collect())
    assert s.ok
    # First sample: load-based estimate (reference formula with real cores,
    # monitor_server.js:76).
    assert s.data["cpu"]["load_1min"] == 2.45
    assert s.data["cpu"]["percent"] == pytest.approx(100 * 2.45 / 8, abs=0.1)
    mem = s.data["memory"]
    assert mem["total"] == 16384000 * 1024
    assert mem["percent"] == pytest.approx(50.0, abs=0.1)
    disk = s.data["disk"]
    assert disk["total"] > 0 and 0 <= disk["percent"] <= 100


def test_host_cpu_percent_from_stat_delta(tmp_path):
    proc = make_proc(tmp_path)
    c = HostCollector(cpu_count=8, proc_root=proc)
    asyncio.run(c.collect())
    (tmp_path / "stat").write_text(STAT_T1)
    s = asyncio.run(c.collect())
    # busy delta = 300, total delta = 700
    assert s.data["cpu"]["percent"] == pytest.approx(100 * 300 / 700, abs=0.1)


def test_host_degrades_per_subsource(tmp_path):
    """Reference contract: errors degrade to empty objects, not a crash
    (monitor_server.js:80) — but tpumon records the error."""
    (tmp_path / "loadavg").write_text(LOADAVG)
    (tmp_path / "stat").write_text(STAT_T0)
    # no meminfo file
    c = HostCollector(cpu_count=8, proc_root=str(tmp_path))
    s = asyncio.run(c.collect())
    assert not s.ok
    assert s.data["memory"] == {}
    assert s.data["cpu"]["load_1min"] == 2.45  # other sub-sources still work
    assert "memory" in s.error
