"""Fake DOM + network adapters for executing dashboard.js under jsmini.

dashboard.js touches the document only through the injected ``doc``
adapter and a small element contract (textContent/innerHTML/style/
classList/append/appendChild/replaceChildren/onclick/title/dataset/
colSpan — see its header comment). This module implements that contract
with plain dicts (jsmini member access/assignment works on dicts, and
JS closures stored into them are Python-callable), plus helpers to walk
the built tree in assertions.
"""

from __future__ import annotations

from typing import Any

from tests.canvas2d import RecordingCtx


def tojs(v):
    """JSON -> jsmini values: numbers are floats in the interpreter
    (json.loads yields ints for whole numbers; the browser has only
    doubles, so this mirrors reality rather than papering over it).
    Shared by tests/test_dashboard_js.py and tools/render_dashboard.py
    so the committed artifact and the tests use one coercion rule."""
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, int):
        return float(v)
    if isinstance(v, list):
        return [tojs(x) for x in v]
    if isinstance(v, dict):
        return {k: tojs(x) for k, x in v.items()}
    return v


def make_el(tag: str) -> dict:
    """One fake element. Children live under "_children"; everything
    else is the element contract dashboard.js uses."""
    el: dict[str, Any] = {
        "_tag": tag,
        "_children": [],
        "textContent": "",
        "innerHTML": "",
        "title": "",
        "className": "",
        "colSpan": 0,
        "onclick": None,
        "style": {},
        "dataset": {},
    }

    def append_child(child):
        el["_children"].append(child)
        return child

    def append(*children):
        el["_children"].extend(children)

    def replace_children(*children):
        el["_children"] = list(children)

    classes: set[str] = set()

    def cl_add(name):
        classes.add(name)

    def cl_remove(name):
        classes.discard(name)

    def cl_toggle(name, force=None):
        on = (name not in classes) if force is None else bool(force)
        (classes.add if on else classes.discard)(name)
        return on

    def cl_contains(name):
        return name in classes

    el["appendChild"] = append_child
    el["append"] = append
    el["replaceChildren"] = replace_children
    el["classList"] = {
        "add": cl_add,
        "remove": cl_remove,
        "toggle": cl_toggle,
        "contains": cl_contains,
        "_classes": classes,
    }
    return el


def all_text(el: dict) -> str:
    """Concatenated textContent of an element's subtree (innerHTML
    fragments included verbatim)."""
    parts = [str(el.get("textContent") or ""), str(el.get("innerHTML") or "")]
    for ch in el.get("_children", []):
        parts.append(all_text(ch))
    return " ".join(p for p in parts if p)


def find_by_class(el: dict, cls: str) -> list[dict]:
    out = []
    if cls in str(el.get("className", "")).split():
        out.append(el)
    for ch in el.get("_children", []):
        out.extend(find_by_class(ch, cls))
    return out


class FakeDoc:
    """doc adapter: elements by id (created on demand, so the test
    doesn't have to enumerate every id in dashboard.html) + registered
    selector results for queryAll."""

    def __init__(self) -> None:
        self.els: dict[str, dict] = {}
        self.queries: dict[str, list[dict]] = {}

    def el(self, el_id: str) -> dict:
        if el_id not in self.els:
            self.els[el_id] = make_el("div")
            self.els[el_id]["_id"] = el_id
        return self.els[el_id]

    def js(self) -> dict:
        return {
            "el": self.el,
            "mk": make_el,
            "queryAll": lambda sel: self.queries.get(sel, []),
        }


class FakeNet:
    """net adapter: synchronous, serves canned payloads per URL.

    ``routes`` maps a URL (exact, or prefix ending the query string at
    '?') to a JSON-shaped payload; missing/None routes deliver null to
    the callback (the fetch-failed path). POSTs are recorded.
    """

    def __init__(self, routes: dict[str, Any] | None = None) -> None:
        self.routes = dict(routes or {})
        self.gets: list[str] = []
        self.posts: list[tuple[str, Any]] = []

    def _lookup(self, url: str):
        if url in self.routes:
            return self.routes[url]
        base = url.split("?", 1)[0]
        return self.routes.get(base)

    def js(self) -> dict:
        def get_json(url, cb):
            self.gets.append(url)
            cb(self._lookup(url))

        def post_json(url, payload, done):
            self.posts.append((url, payload))
            done()

        return {"getJson": get_json, "postJson": post_json}


class FakeEnv:
    def __init__(self, now_ms: float = 1_700_000_000_000.0) -> None:
        self.now = now_ms

    def js(self) -> dict:
        return {
            "nowMs": lambda: self.now,
            "timeStr": lambda: "12:34:56",
            "localeTime": lambda ms: f"t{int(ms / 1000) % 100000}",
            "winWidth": lambda: 1280.0,
        }


class FakeSurfaces:
    """mkSurface factory: one RecordingCtx per canvas element, with a
    fixed geometry — tests read .ops per canvas id afterwards."""

    def __init__(self, w: float = 600.0, h: float = 190.0) -> None:
        self.w, self.h = w, h
        self.by_id: dict[str, RecordingCtx] = {}

    def mk_surface(self, canvas_el: dict) -> dict:
        cid = canvas_el.get("_id") or f"anon{len(self.by_id)}"
        ctx = self.by_id.setdefault(cid, RecordingCtx())
        geom = {
            "w": self.w, "h": self.h,
            "l": 44.0, "r": 10.0, "t": 8.0, "b": 20.0,
        }
        return {"geom": lambda: geom, "ctx": ctx.js}

    def ops(self, cid: str) -> list:
        ctx = self.by_id.get(cid)
        return list(ctx.ops) if ctx else []
