"""Mixed-fleet soak (ISSUE 15 acceptance): fake TPU leaves and fake GPU
nodes federate into ONE aggregator/root tree — real servers, live
sampler loops, the same harness as the federation-tree soak:

- the root's fleet view labels every slice with its accelerator kind
  and partitions chip counts per family (`fleet.by_accel`);
- a distributed `topk(...) by (accel)` fleet query returns BOTH
  partitions, evaluated leaf-side (partial aggregates only — never raw
  points upstream);
- killing a GPU node marks its slice dark at the root exactly like a
  TPU leaf;
- a pre-upgrade leaf (streaming the old 16-field wire layout without
  `accel_kind`) still federates, its slices defaulting to "tpu";
- the aggregator's merged accel view, exporter and /api/gpu/metrics all
  thread the family through.
"""

import asyncio
import json
import time
import urllib.parse
import urllib.request

from tests.test_federation_tree import _mk, wait_until
from tests.test_server_api import get_json


def _get_text(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.read().decode()


def _slice_rows_sync(port):
    """Raw slice-row LIST (slice ids are only unique within a leaf —
    the TPU leaf and the GPU node both report a 'slice-0', so the
    tree soak's id-keyed dict would collapse them)."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/federation", timeout=5
        ) as r:
            return json.loads(r.read()).get("slices", [])
    except OSError:
        return []


async def _node_row(port, node):
    rows = await asyncio.to_thread(_slice_rows_sync, port)
    return next((r for r in rows if r.get("node") == node), None)


def test_mixed_fleet_soak():
    async def scenario():
        # --- tree: root <- agg <- {TPU leaf, GPU node, old peer} -----
        root_s, root_srv = _mk(
            TPUMON_ACCEL_BACKEND="none",
            TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="root",
        )
        await root_srv.start()
        await root_s.start()
        agg_s, agg_srv = _mk(
            TPUMON_ACCEL_BACKEND="none",
            TPUMON_FEDERATION_ROLE="aggregator",
            TPUMON_FEDERATION_NODE="agg0",
            TPUMON_FEDERATE_UP=f"http://127.0.0.1:{root_srv.port}",
        )
        await agg_srv.start()
        await agg_s.start()
        await agg_s.uplink.start()

        def leaf(name, backend, **env):
            s, srv = _mk(
                TPUMON_ACCEL_BACKEND=backend,
                TPUMON_FEDERATION_NODE=name,
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_srv.port}",
                # Per-chip history ON (the tree soak disables it): the
                # by-(accel) fleet query reads chip.* at the leaves.
                TPUMON_HISTORY_PER_CHIP="64",
                **env,
            )
            s.uplink.backoff_max_s = 0.4
            return s, srv

        tpu_s, tpu_srv = leaf("tpuleaf", "fake:v5e-8@tpuleaf")
        gpu_s, gpu_srv = leaf("gpunode", "gpufake:dgx-a100-8@gpunode")
        for s, srv in ((tpu_s, tpu_srv), (gpu_s, gpu_srv)):
            await srv.start()
            await s.start()
            await s.uplink.start()

        # --- both kinds land, labeled, at the root -------------------
        async def both_kinds():
            t = await _node_row(root_srv.port, "tpuleaf")
            g = await _node_row(root_srv.port, "gpunode")
            return (
                t and g
                and t["health"] == "ok" and g["health"] == "ok"
                and t.get("accel_kind") == "tpu"
                and g.get("accel_kind") == "gpu"
            )

        await wait_until(both_kinds, "root labels both accelerator kinds")
        fed = await asyncio.to_thread(get_json, root_srv.port, "/api/federation")
        by_accel = fed["fleet"]["by_accel"]
        assert by_accel["tpu"]["chips"] == 8, by_accel
        assert by_accel["gpu"]["chips"] == 8, by_accel
        # The aggregator's merged accel view carries both families...
        d = await asyncio.to_thread(get_json, agg_srv.port, "/api/accel/metrics")
        kinds = {c["accel_kind"] for c in d["chips"]}
        assert kinds == {"tpu", "gpu"} and len(d["chips"]) == 16
        # ...the slice rollup JSON says which is which...
        slice_kinds = {s["slice"]: s["accel_kind"] for s in d["slices"]}
        assert set(slice_kinds.values()) == {"tpu", "gpu"}
        # ...the exporter's chip gauges carry the accel label...
        metrics = await asyncio.to_thread(_get_text, agg_srv.port, "/metrics")
        assert 'accel="gpu"' in metrics and 'accel="tpu"' in metrics
        # ...and the reference-compat view names GPU rows as GPUs.
        gpu_compat = await asyncio.to_thread(
            get_json, gpu_srv.port, "/api/gpu/metrics"
        )
        assert all(row["name"].startswith("GPU a100") for row in gpu_compat)

        # --- fleet query partitions per family, leaf-evaluated -------
        expr = "topk(5, rate(chip.hbm[5s])) by (accel)"
        # rate() needs >= 2 points per series: let a few ticks land.
        await asyncio.sleep(0.5)

        async def fleet_answer():
            out = await root_s.federation.fleet_query(expr, timeout_s=5.0)
            fams = {r["labels"].get("accel") for r in out["result"]}
            return out if fams == {"tpu", "gpu"} else None

        out = await wait_until(fleet_answer, "by (accel) fleet partitions")
        assert out["fleet"] is True and not out.get("partial"), out
        per_fam: dict = {}
        for r in out["result"]:
            per_fam.setdefault(r["labels"]["accel"], []).append(r)
        # k rows per family (8 chips each, k=5), full labels kept.
        assert all(len(rows) == 5 for rows in per_fam.values()), per_fam
        assert all(r["labels"].get("chip") for r in out["result"])
        # Leaves answered sub-queries with partial aggregates (TPWR
        # frames over the open uplink), never raw points: bytes per
        # answer stay far under one chip keyframe.
        for s in (tpu_s, gpu_s):
            assert s.uplink.queries_answered >= 1
            per_answer = s.uplink.query_bytes / s.uplink.queries_answered
            assert per_answer < s.uplink.enc.stats["keyframe_bytes"], (
                per_answer, s.uplink.enc.stats["keyframe_bytes"])
        # The HTTP route serves the same thing (fleet=1 at the root).
        q = urllib.parse.quote(expr)
        http_out = await asyncio.to_thread(
            get_json, root_srv.port, f"/api/query?query={q}&fleet=1"
        )
        assert {
            r["labels"].get("accel") for r in http_out["result"]
        } == {"tpu", "gpu"}

        # --- a pre-upgrade peer (no accel_kind column) federates -----
        old_s, old_srv = leaf("oldleaf", "fake:v5e-4@oldleaf")
        orig_payload = old_s.uplink._payload

        def pre_accel_payload(ts):
            v, fields, rows = orig_payload(ts)
            assert fields[-1] == "accel_kind"
            return v, fields[:-1], [r[:-1] for r in rows]

        old_s.uplink._payload = pre_accel_payload
        await old_srv.start()
        await old_s.start()
        await old_s.uplink.start()

        async def old_peer_lands():
            r = await _node_row(root_srv.port, "oldleaf")
            return r and r["health"] == "ok" and r.get("accel_kind") == "tpu"

        await wait_until(
            old_peer_lands, "pre-accel_kind peer federates as tpu"
        )

        # --- kill the GPU node: dark at the root, like any leaf ------
        await gpu_s.stop()
        await gpu_srv.stop()

        async def gpu_dark():
            r = await _node_row(root_srv.port, "gpunode")
            return r and r["health"] == "dark" and r["accel_kind"] == "gpu"

        await wait_until(gpu_dark, "dark GPU node propagates to root")
        ev = await asyncio.to_thread(
            get_json, agg_srv.port, "/api/events?kind=federation"
        )
        assert any(
            e["severity"] == "serious" and "gpunode" in e["msg"]
            and "dark" in e["msg"]
            for e in ev["events"]
        ), ev["events"]
        # The dark partition stays visible in the per-family fleet view.
        fed = await asyncio.to_thread(get_json, root_srv.port, "/api/federation")
        assert fed["fleet"]["by_accel"]["gpu"]["slices"] >= 1
        assert fed["fleet"]["dark_slices"] >= 1

        for s, srv in (
            (tpu_s, tpu_srv), (old_s, old_srv),
            (agg_s, agg_srv), (root_s, root_srv),
        ):
            await s.stop()
            await srv.stop()

    asyncio.run(scenario())


def test_mixed_chips_one_sampler_rollup_and_augmenter():
    """Below the tree: one sampler whose accel view carries both
    families (a TPU fake merged with GPU chips) derives per-family
    slice views, exporter labels and query `accel` labels from the
    same ChipSample schema — no federation required."""
    from tpumon.collectors import Sample
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.collectors.gpu_fake import FakeGpuCollector
    from tpumon.config import load_config
    from tpumon.exporter import render_exporter
    from tpumon.sampler import Sampler

    class MixedCollector:
        name = "accel"

        def __init__(self):
            self.tpu = FakeTpuCollector(topology="v5e-4", clock=lambda: 800.0)
            self.gpu = FakeGpuCollector(
                topology="dgx-a100-8", clock=lambda: 800.0)

        async def collect(self):
            return Sample(
                source="accel", ok=True,
                data=self.tpu.chips() + self.gpu.chips(),
            )

    cfg = load_config(env={
        "TPUMON_COLLECTORS": "accel", "TPUMON_K8S_MODE": "none",
    })
    sampler = Sampler(cfg, accel=MixedCollector())
    asyncio.run(sampler.tick_fast())
    views = {v.slice_id: v for v in sampler.slices()}
    assert views["slice-0"].accel_kind == "tpu"
    assert views["gpu-0"].accel_kind == "gpu"
    text = render_exporter(sampler)
    assert 'accel="gpu"' in text and 'accel="tpu"' in text
    # Query label derivation through the sampler's augmenter.
    out = sampler.query.instant(
        "count(chip.mxu) by (accel)", at=time.time())["result"]
    got = {r["labels"]["accel"]: r["value"] for r in out}
    assert got == {"tpu": 4.0, "gpu": 8.0}
    res = sampler.query.instant(
        'avg(chip.mxu{accel="gpu"})', at=time.time())["result"]
    assert len(res) == 1 and res[0]["value"] is not None
