"""Known-bad fixture: every thread-discipline rule trips once."""

import threading
from http.server import ThreadingHTTPServer


def fire_and_forget(work):
    # neither daemon nor joined -> threads.undaemonized-unjoined
    threading.Thread(target=work).start()


def start_server(handler):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    # shutdown() below but the listening socket is never closed
    # -> threads.serve-forever-unclosed
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def stop_server(srv):
    srv.shutdown()


class Poller:
    # spawns a background thread, defines no stop()/close()
    # -> threads.no-stop; self.state mutated from both the thread body
    # and an owner method without a lock -> threads.unguarded-attr
    def __init__(self):
        self.state = None
        self._lock = threading.Lock()

    def start(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while True:
            self.state = "polled"

    def reset(self):
        self.state = None


class Watcher:
    # a well-formed stoppable component (for the owner rule below)
    def __init__(self):
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def stop(self):
        self._thread.join(timeout=1.0)


class Owner:
    # holds a Watcher but never stops it -> threads.stoppable-not-stopped
    def __init__(self):
        self._w = Watcher()
        self._w.start()
