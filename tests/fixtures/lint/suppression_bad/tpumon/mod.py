"""Known-bad fixture: malformed suppressions."""

import threading


def fire(work):
    # reasonless: the underlying finding is suppressed, but the
    # missing-reason finding (unsuppressable) keeps the run red.
    threading.Thread(target=work).start()  # tpulint: disable=threads


def fire2(work):
    # names a pass that doesn't exist
    # tpulint: disable=nosuchpass (this pass is fictional)
    threading.Thread(target=work).start()
