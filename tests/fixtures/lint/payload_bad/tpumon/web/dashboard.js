/* Known-bad fixture: reads the pre-rename key + a typo'd chip field,
 * and polls a route the server does not register. */
"use strict";
let streamData = null;

function applyHost(host) {
  const pct = host.cpu;
  return pct;
}

function renderChips(accel) {
  const grid = accel.chps;        /* typo: server emits "chips" */
  const err = accel.health.error; /* fine: emitted */
  return [grid, err];
}

function renderStream() {
  applyHost(streamData.host);     /* server renamed this key */
  renderChips(streamData.accel);
}

function fetchAll() {
  net.getJson("/api/accel/metrics", accel => renderChips(accel));
  net.getJson("/api/chips", d => d.rows);  /* route never registered */
}
