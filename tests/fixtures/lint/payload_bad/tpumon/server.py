"""Known-bad fixture: the realtime payload and dashboard.js disagree."""


class MonitorServer:
    def __init__(self):
        self._cached_routes: dict = {
            "/api/accel/metrics": (("accel",), self._api_accel),
        }

    def _api_accel(self) -> dict:
        return {"chips": [], "health": {"error": None}}

    def realtime_payload(self) -> dict:
        return {
            # Renamed: dashboard.js still reads streamData.host.
            "hosts": {"cpu": 1.0},
            "accel": self._api_accel(),
            # Nobody anywhere reads this: dead SSE weight.
            "legacy_debug": 1,
        }

    def routes(self):
        return ("/api/accel/metrics",)
