"""Fixture: a well-formed suppression (with reason) silences a finding."""

import threading


def fire(work):
    # tpulint: disable=threads.undaemonized-unjoined (fixture: the worker owns its own lifetime)
    threading.Thread(target=work).start()
