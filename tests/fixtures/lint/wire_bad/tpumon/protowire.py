"""Known-bad fixture: _CT_BAD has an encoder branch but no decoder
branch and no truncation-test reference."""

_CT_GOOD = 1
_CT_BAD = 2


def _encode_col(out, col, ctype):
    if ctype == _CT_GOOD:
        out += b"g"
    elif ctype == _CT_BAD:
        out += b"b"


def _decode_col(blob, pos, nrows, ctype):
    if ctype == _CT_GOOD:
        return ["g"] * nrows, pos
    raise ValueError("unknown ctype")
