"""Fixture test file: truncation coverage references _CT_GOOD only."""


def test_truncation_at_every_prefix():
    _CT_GOOD = 1
    assert _CT_GOOD == 1
