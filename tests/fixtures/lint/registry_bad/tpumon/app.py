"""Known-bad fixture: a CLI flag writing an unaccepted config key."""


def main(argv):
    overrides = {}
    for arg in argv:
        if arg == "--port":
            overrides["port"] = 1
        elif arg == "--ghost":
            overrides["nope"] = 1  # unknown key + undocumented flag
    return overrides
