"""Known-bad fixture: records an unregistered event kind."""


class Engine:
    def __init__(self, journal):
        self.journal = journal

    def fire(self):
        self.journal.record("phantom", "info", "engine", "boom")
