"""Known-bad fixture event registry."""

KINDS = ("alert",)


class EventJournal:
    def record(self, kind, severity, source, message):
        assert kind in KINDS
