"""Known-bad serving exposition for the registry pass: one replica
gauge family documented in docs/perf.md (stays clean), one ghost
family no doc mentions (fires registry.metric-undocumented anchored
here, not in exporter.py)."""


def metrics_text(rows):
    lines = []
    for replica, slots in rows:
        lines.append(
            'tpumon_serving_replica_slots_available{replica="%s"} %d'
            % (replica, slots))
        lines.append(
            'tpumon_serving_replica_ghost_gauge{replica="%s"} 1'
            % replica)
    # The family literals below are what the scanner keys on.
    _ = "tpumon_serving_replica_slots_available"
    _ = "tpumon_serving_replica_ghost_gauge"
    return "\n".join(lines)
