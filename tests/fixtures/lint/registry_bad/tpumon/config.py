"""Known-bad fixture: the loader accepts a key Config doesn't have."""

from dataclasses import dataclass


@dataclass(frozen=True)
class Config:
    port: int = 8888


_SCALAR_FIELDS: dict = {
    "port": int,
    "ghost_key": str,  # no Config field, not in README -> 2 findings
}
