"""Known-bad fixture: undocumented federation + actuation gauges."""


def render(w):
    g = w.gauge("tpumon_federation_ghost_gauge", "documented nowhere")
    g.add({}, 1.0)
    a = w.gauge("tpumon_actuate_ghost_gauge", "documented nowhere")
    a.add({}, 1.0)
