"""Known-bad fixture: an undocumented federation gauge."""


def render(w):
    g = w.gauge("tpumon_federation_ghost_gauge", "documented nowhere")
    g.add({}, 1.0)
