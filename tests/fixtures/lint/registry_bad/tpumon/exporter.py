"""Known-bad fixture: undocumented federation + actuation + accel gauges."""


def render(w):
    g = w.gauge("tpumon_federation_ghost_gauge", "documented nowhere")
    g.add({}, 1.0)
    a = w.gauge("tpumon_actuate_ghost_gauge", "documented nowhere")
    a.add({}, 1.0)
    # ISSUE 15: tpu_* chip/slice families are pinned to
    # docs/federation.md's mixed-fleet table — an accel-labeled family
    # nobody documented must fire registry.metric-undocumented.
    t = w.gauge("tpu_ghost_accel_gauge", "documented nowhere")
    t.add({"accel": "gpu"}, 1.0)
