"""Known-bad fixture: undocumented federation + actuation + accel gauges."""


def render(w):
    g = w.gauge("tpumon_federation_ghost_gauge", "documented nowhere")
    g.add({}, 1.0)
    a = w.gauge("tpumon_actuate_ghost_gauge", "documented nowhere")
    a.add({}, 1.0)
    # ISSUE 19: tpumon_federation_freshness_* families are pinned to
    # docs/observability.md on top of the federation pin — this ghost
    # is in neither doc, so it fires for both prefixes.
    f = w.gauge("tpumon_federation_freshness_ghost_ms", "documented nowhere")
    f.add({"node": "leaf0"}, 1.0)
    # ISSUE 15: tpu_* chip/slice families are pinned to
    # docs/federation.md's mixed-fleet table — an accel-labeled family
    # nobody documented must fire registry.metric-undocumented.
    t = w.gauge("tpu_ghost_accel_gauge", "documented nowhere")
    t.add({"accel": "gpu"}, 1.0)
