"""Fixture: query-function registry that drifted from its docs —
``mystery_fn`` is declared but undocumented (query-func-undocumented),
and the fixture docs/query.md documents ``made_up`` which is not
declared (query-func-phantom)."""

RANGE_FUNCTIONS = ("rate", "mystery_fn")
AGG_OPS = ("topk",)
FUNCTIONS = RANGE_FUNCTIONS + AGG_OPS
