"""Known-bad fixture: a federation stage the doc never mentions."""

# `fed.push` has a doc row; `fed.ghost_stage` is documented nowhere and
# must fire registry.trace-stage-undocumented.
FED_STAGES = ("fed.push", "fed.ghost_stage")
