"""Route map: /api/known only."""


ROUTES = ("/api/known", "/api/ghost")  # /api/ghost documented nowhere
