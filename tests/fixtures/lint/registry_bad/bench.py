"""Known-bad fixture: a key of record no phase ever produces."""

KEYS_OF_RECORD = (
    "produced_key",
    "never_set_key",
)


def phase():
    return {"produced_key": 1.0}
