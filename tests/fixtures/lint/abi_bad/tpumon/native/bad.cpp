// Known-bad fixture: every flavor of ctypes <-> C ABI drift.

#include <cstdint>

extern "C" {

struct FixSample {
  double a;
  int32_t b;
};

// Exported but never bound in __init__.py -> abi.unbound-export.
int tpumon_fix_unbound(int a) { return a; }

// Python binds only 2 of these 3 parameters -> abi.arity-mismatch.
int64_t tpumon_fix_drift(int64_t n, const double* vals, double scale) {
  return n + (int64_t)scale + (vals ? 1 : 0);
}

// Python binds argtypes [c_int32] for a double -> abi.type-mismatch.
int tpumon_fix_badtype(double x) { return (int)x; }

// Python's FixStruct declares (c_double, c_double) -> abi.struct-mismatch.
int tpumon_fix_struct(FixSample* s) { return s ? s->b : 0; }

// Python binds restype only, no argtypes -> abi.missing-argtypes.
int tpumon_fix_noargs(int a) { return a; }

// Python expects 1 -> abi.version-mismatch.
int tpumon_fix_abi_version(void) { return 2; }

// Bound but never compared against a constant -> abi.version-unchecked.
int tpumon_fix2_abi_version(void) { return 1; }

}  // extern "C"

extern "C" {
// Binding assigns argtypes but no restype; ctypes' default c_int
// would silently mangle the double -> abi.missing-restype.
double tpumon_fix_noret(void) { return 0.5; }
}
