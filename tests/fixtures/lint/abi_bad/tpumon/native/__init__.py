"""Known-bad fixture: bindings drifted from the C declarations."""

import ctypes

FIX_ABI_VERSION = 1


class FixStruct(ctypes.Structure):
    _fields_ = [
        ("a", ctypes.c_double),
        ("b", ctypes.c_double),  # C declares int32_t b
    ]


def load():
    lib = ctypes.CDLL("libfix.so")
    # Arity drift: the C function takes (n, vals, scale).
    lib.tpumon_fix_drift.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.tpumon_fix_drift.restype = ctypes.c_int64
    # Type drift: C takes a double.
    lib.tpumon_fix_badtype.argtypes = [ctypes.c_int32]
    lib.tpumon_fix_badtype.restype = ctypes.c_int
    # Struct layout drift: FixStruct's second field is c_double.
    lib.tpumon_fix_struct.argtypes = [ctypes.POINTER(FixStruct)]
    lib.tpumon_fix_struct.restype = ctypes.c_int
    # Missing argtypes on a function that takes parameters.
    lib.tpumon_fix_noargs.restype = ctypes.c_int
    # Binding for a symbol no .cpp exports.
    lib.tpumon_fix_gone.argtypes = []
    lib.tpumon_fix_gone.restype = ctypes.c_int
    lib.tpumon_fix_abi_version.restype = ctypes.c_int
    lib.tpumon_fix2_abi_version.restype = ctypes.c_int
    if lib.tpumon_fix_abi_version() != FIX_ABI_VERSION:
        return None
    return lib


def load_more(lib):
    # Missing restype on a double-returning function.
    lib.tpumon_fix_noret.argtypes = []
    return lib
