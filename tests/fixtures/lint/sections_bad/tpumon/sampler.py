"""Known-bad fixture: bumps an undeclared section name."""


class Sampler:
    def publish(self, sample):
        self.latest[sample.source] = sample
        self.clock.bump(sample.source)  # dynamic: covers host/accel

    def publish_alerts(self):
        self.clock.bump("typo_section")  # undeclared -> finding
