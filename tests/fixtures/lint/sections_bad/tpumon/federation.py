"""Known-bad fixture: publishes fan-in state without an epoch bump."""


class Hub:
    def land_frame(self, ns, rows):
        ns.slice_rows = rows  # published, but no bump -> finding
        ns.status = "ok"

    def mark_dark(self, ns):
        ns.status = "down"
        self.clock.bump("accel")  # paired: no finding here
