"""Known-bad fixture: publishes fan-in state without an epoch bump —
directly, and through a helper call (the interprocedural case)."""


class Hub:
    def land_frame(self, ns, rows):
        ns.slice_rows = rows  # published, but no bump -> finding
        ns.status = "ok"

    def mark_dark(self, ns):
        ns.status = "down"
        self.clock.bump("accel")  # paired: no finding here

    # Interprocedural: the mutation hides in a helper; the only caller
    # never bumps either -> the helper is flagged.
    def apply_rollup(self, ns, rows):
        self._store_rows(ns, rows)

    def _store_rows(self, ns, rows):
        ns.chips = rows  # published via helper, no bump on any path

    # Covered helper: every caller bumps, so the helper is clean.
    def connect(self, ns):
        self._set_status(ns, "ok")
        self.clock.bump("accel")

    def _set_status(self, ns, status):
        ns.status = status  # callers all bump: no finding


class Uplink:
    # Same bare name as Hub.connect (which bumps): the class-qualified
    # call graph must NOT let Hub's bump mask this bump-free publish.
    def connect(self, ns):
        ns.connected = True  # published, no bump on any path -> finding
