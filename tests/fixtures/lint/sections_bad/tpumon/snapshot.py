"""Known-bad fixture: 'ghost' is declared but nothing ever bumps it."""

SECTIONS = ("host", "accel", "ghost")
