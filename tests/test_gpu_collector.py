"""GPU collector family (ISSUE 15, tpumon/collectors/gpu.py +
gpu_fake.py): nvidia-smi CSV and DCGM exposition parsers normalizing
into the accelerator-generic ChipSample (SM%→duty, VRAM→HBM,
NVLink→ICI, provenance in counter_source, accel_kind="gpu"), the fake
DGX geometries mirroring accel_fake, the accel_backend factory grammar,
and honest-degraded behavior when the binary/exporter is absent."""

import asyncio
import time

import pytest

from tpumon.collectors.accel import make_accel_collector
from tpumon.collectors.gpu import (
    DcgmCollector,
    NvidiaSmiCollector,
    normalize_gpu_kind,
    parse_dcgm_text,
    parse_nvidia_smi_csv,
)
from tpumon.collectors.gpu_fake import (
    GPU_FAKE_TOPOLOGIES,
    VRAM_BYTES_BY_KIND,
    FakeGpuCollector,
)
from tpumon.config import load_config
from tpumon.topology import accel_terms, slice_views

SMI_OUTPUT = """\
0, NVIDIA A100-SXM4-80GB, 93, 40536, 81920, 61
1, NVIDIA A100-SXM4-80GB, 5, 1024, 81920, [N/A]
2, NVIDIA H100 80GB HBM3, [N/A], [N/A], 81920, 48
"""

DCGM_OUTPUT = """\
# HELP DCGM_FI_DEV_GPU_UTIL GPU utilization
# TYPE DCGM_FI_DEV_GPU_UTIL gauge
DCGM_FI_DEV_GPU_UTIL{gpu="0",UUID="GPU-x",modelName="NVIDIA H100 80GB HBM3",Hostname="node1"} 77
DCGM_FI_DEV_FB_USED{gpu="0",modelName="NVIDIA H100 80GB HBM3",Hostname="node1"} 40000
DCGM_FI_DEV_FB_FREE{gpu="0",Hostname="node1"} 41920
DCGM_FI_DEV_GPU_TEMP{gpu="0",Hostname="node1"} 55
DCGM_FI_PROF_NVLINK_TX_BYTES{gpu="0",Hostname="node1"} 123456789
DCGM_FI_PROF_NVLINK_RX_BYTES{gpu="0",Hostname="node1"} 98765432
DCGM_FI_DEV_XID_ERRORS{gpu="0",Hostname="node1"} 0
DCGM_FI_DEV_GPU_UTIL{gpu="1",modelName="NVIDIA H100 80GB HBM3",Hostname="node1"} 12
DCGM_FI_DEV_XID_ERRORS{gpu="1",Hostname="node1"} 74
DCGM_FI_DEV_GPU_UTIL{gpu="2",modelName="NVIDIA H100 80GB HBM3",Hostname="node1"} 33
DCGM_FI_DEV_XID_ERRORS{gpu="2",Hostname="node1"} 13
"""


def test_normalize_gpu_kind():
    assert normalize_gpu_kind("NVIDIA A100-SXM4-80GB") == "a100"
    assert normalize_gpu_kind("NVIDIA H100 80GB HBM3") == "h100"
    assert normalize_gpu_kind("Tesla V100-SXM2-16GB") == "v100"
    # Token-bounded: an L40S is not an L4, an A100 is not an A10.
    assert normalize_gpu_kind("NVIDIA L40S") == "l40s"
    assert normalize_gpu_kind("NVIDIA L4") == "l4"
    assert normalize_gpu_kind("NVIDIA A10G") == "a10g"
    assert normalize_gpu_kind("Weird Device") == "Weird Device"


def test_parse_nvidia_smi_csv():
    chips = parse_nvidia_smi_csv(SMI_OUTPUT, "dgx-0")
    assert [c.chip_id for c in chips] == [
        "dgx-0/gpu-0", "dgx-0/gpu-1", "dgx-0/gpu-2",
    ]
    c0 = chips[0]
    # The reference's record (monitor_server.js:90) under ChipSample
    # names: utilization → duty, memoryUsed/Total (MiB) → hbm bytes.
    assert c0.kind == "a100" and c0.accel_kind == "gpu"
    assert c0.mxu_duty_pct == 93.0
    assert c0.hbm_used == 40536 * 2**20
    assert c0.hbm_total == 81920 * 2**20
    assert c0.temp_c == 61.0
    assert c0.counter_source == "nvidia-smi"
    # [N/A] cells are honest Nones, not zeros.
    assert chips[1].temp_c is None
    assert chips[2].mxu_duty_pct is None and chips[2].hbm_used is None
    # Garbage lines are skipped, not fatal.
    assert parse_nvidia_smi_csv("not,a,row\n\n", "h") == []


def test_parse_dcgm_text():
    chips = parse_dcgm_text(DCGM_OUTPUT)
    assert [c.chip_id for c in chips] == [
        "node1/gpu-0", "node1/gpu-1", "node1/gpu-2",
    ]
    c0 = chips[0]
    assert c0.kind == "h100" and c0.accel_kind == "gpu"
    assert c0.mxu_duty_pct == 77.0
    assert c0.hbm_used == 40000 * 2**20
    assert c0.hbm_total == (40000 + 41920) * 2**20  # FB_USED + FB_FREE
    assert c0.temp_c == 55.0
    assert c0.ici_tx_bytes == 123456789
    assert c0.ici_rx_bytes == 98765432
    assert c0.ici_link_health == 0
    assert c0.counter_source == "dcgm"
    # Only NVLink/bus XIDs (62/74/79) degrade link health; a benign
    # application-level XID (13: a crashed user process — DCGM keeps
    # the LAST code forever) must NOT read as a link problem, or a
    # healthy GPU pages serious until driver reload.
    assert chips[1].ici_link_health == 7  # XID 74: NVLink error
    assert chips[2].ici_link_health == 0  # XID 13: benign, healthy link
    assert chips[1].hbm_total is None  # no FB rows → honest None


def test_fake_gpu_geometries():
    for topo, (kind, hosts, per_host, hps) in GPU_FAKE_TOPOLOGIES.items():
        chips = FakeGpuCollector(topology=topo, clock=lambda: 500.0).chips()
        assert len(chips) == hosts * per_host, topo
        assert all(c.accel_kind == "gpu" and c.kind == kind for c in chips)
        assert all(c.hbm_total == VRAM_BYTES_BY_KIND[kind] for c in chips)
        assert all(
            0 <= c.mxu_duty_pct <= 100 and 0 < c.hbm_used <= c.hbm_total
            for c in chips
        )
    # Multi-node shape: 4 hosts in 2-node partitions → 2 slices.
    pod = FakeGpuCollector(topology="superpod-32", clock=lambda: 500.0)
    views = slice_views(pod.chips())
    assert [v.slice_id for v in views] == ["gpu-0.0", "gpu-0.1"]
    assert all(v.reporting_chips == 16 and v.accel_kind == "gpu"
               for v in views)


def test_fake_gpu_fault_injection_mirrors_tpu_fake():
    g = FakeGpuCollector(topology="dgx-a100-8", clock=lambda: 500.0)
    g.kill_host("gpu-node-0")
    assert g.chips() == []
    g.revive_host("gpu-node-0")
    g.set_override("gpu-node-0/gpu-3", mxu_duty_pct=1.5, ici_link_health=9)
    over = {c.chip_id: c for c in g.chips()}["gpu-node-0/gpu-3"]
    assert over.mxu_duty_pct == 1.5 and over.ici_link_health == 9
    with pytest.raises(ValueError):
        FakeGpuCollector(topology="dgx-nope")


def test_factory_backend_grammar():
    def mk(backend):
        return make_accel_collector(
            load_config(env={"TPUMON_ACCEL_BACKEND": backend})
        )

    col = mk("gpufake:dgx-h100-8@n7+faults")
    assert isinstance(col, FakeGpuCollector)
    assert col.topology == "dgx-h100-8" and col.host_prefix == "n7"
    assert col.fault_episodes is True
    s = asyncio.run(col.collect())
    assert s.ok and len(s.data) == 8 and s.data[0].host == "n7-0"

    smi = mk("nvidia-smi:/opt/bin/nvidia-smi")
    assert isinstance(smi, NvidiaSmiCollector)
    assert smi.smi_path == "/opt/bin/nvidia-smi"
    assert isinstance(mk("nvidia-smi"), NvidiaSmiCollector)

    dcgm = mk("dcgm:http://gpu-node:9400")
    assert isinstance(dcgm, DcgmCollector)
    assert dcgm.url == "http://gpu-node:9400/metrics"

    with pytest.raises(ValueError):
        mk("gpufake:not-a-topology")


def test_nvidia_smi_missing_binary_degrades_honestly():
    s = asyncio.run(
        NvidiaSmiCollector(smi_path="/nonexistent/nvidia-smi").collect()
    )
    assert s.ok is False and s.data == []
    assert "not found" in (s.error or "")


def test_dcgm_unreachable_degrades_honestly():
    c = DcgmCollector(url="http://127.0.0.1:1/metrics", timeout_s=0.2)
    s = asyncio.run(c.collect())
    assert s.ok is False and s.data == []
    assert "dcgm" in (s.error or "")


def test_accel_terms_vocabulary():
    assert accel_terms("tpu") == {"duty": "MXU", "mem": "HBM", "link": "ICI"}
    assert accel_terms("gpu") == {"duty": "SM", "mem": "VRAM", "link": "NVLink"}
    # Unknown/absent kinds read as TPU — the pre-accel_kind default.
    assert accel_terms(None)["mem"] == "HBM"
    assert accel_terms("npu")["duty"] == "MXU"


def test_gpu_chips_through_alert_engine_speak_gpu_terms():
    """Kind-aware alert text (ISSUE 15 satellite): the same rule keys
    fire, but a GPU chip's title/desc say VRAM/NVLink, not HBM/ICI."""
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds

    g = FakeGpuCollector(topology="dgx-a100-8", clock=lambda: 500.0)
    g.set_override(
        "gpu-node-0/gpu-0",
        hbm_used=int(80 * 1024**3 * 0.97),
        ici_link_health=10,
    )
    engine = AlertEngine(Thresholds())
    chips = g.chips()
    out = engine.evaluate(chips=chips, host=None, pods=None)
    flat = [a for sev in ("critical", "serious", "minor") for a in out[sev]]
    titles = {a["title"] for a in flat}
    assert "VRAM pressure on gpu-node-0/gpu-0" in titles
    assert "NVLink link down on gpu-node-0/gpu-0" in titles
    # Keys keep the stable TPU-native namespace (silences survive).
    keys = {a["key"] for a in flat}
    assert "chip.gpu-node-0/gpu-0.hbm.critical" in keys
    assert "chip.gpu-node-0/gpu-0.ici_down" in keys


def test_exporter_slice_accel_label_stable_across_outage():
    """The tpu_slice_* gauges' `accel` label must not flip on/off when
    a slice goes from reporting to expected-but-absent — that would
    fork the Prometheus series identity exactly when an absence alert
    needs reporting_chips to drop to 0 on the SAME series."""
    from tpumon.config import load_config
    from tpumon.exporter import render_exporter
    from tpumon.metrics_text import parse_metrics_text, samples_by_name
    from tpumon.sampler import Sampler

    gpu = FakeGpuCollector(topology="dgx-a100-8", clock=lambda: 800.0)
    cfg = load_config(env={
        "TPUMON_COLLECTORS": "accel", "TPUMON_K8S_MODE": "none",
        "TPUMON_EXPECTED_SLICE_CHIPS": '{"gpu-0": 8}',
    })
    sampler = Sampler(cfg, accel=gpu)
    asyncio.run(sampler.tick_fast())

    def slice_samples():
        by = samples_by_name(parse_metrics_text(render_exporter(sampler)))
        return {
            tuple(sorted(s.labels.items())): s.value
            for s in by.get("tpu_slice_reporting_chips", [])
        }

    healthy = slice_samples()
    key = (("accel", "gpu"), ("slice", "gpu-0"))
    assert healthy[key] == 8.0
    # Outage: every chip vanishes; the slice survives as an
    # expected-but-absent view — SAME series, value 0.
    gpu.kill_host("gpu-node-0")
    asyncio.run(sampler.tick_fast())
    dark = slice_samples()
    assert dark[key] == 0.0, dark


def test_query_accel_label_stable_across_failed_scrape():
    """The chip-series `accel` query label keeps its last-known family
    when the collector fails a scrape: `{accel="gpu"}` alert/SLO
    matchers must keep matching still-in-lookback GPU series
    mid-incident instead of silently evaluating empty."""
    from tpumon.collectors import Sample
    from tpumon.config import load_config
    from tpumon.sampler import Sampler

    gpu = FakeGpuCollector(topology="dgx-a100-8", clock=lambda: 800.0)

    class Flaky:
        name = "accel"
        fail = False

        async def collect(self):
            if self.fail:
                return Sample(source="accel", ok=False, data=[],
                              error="nvidia-smi exit 1")
            return Sample(source="accel", ok=True, data=gpu.chips())

    flaky = Flaky()
    cfg = load_config(env={
        "TPUMON_COLLECTORS": "accel", "TPUMON_K8S_MODE": "none",
    })
    sampler = Sampler(cfg, accel=flaky)
    asyncio.run(sampler.tick_fast())
    at = time.time()
    ok = sampler.query.instant('count(chip.mxu{accel="gpu"})', at=at)
    assert ok["result"][0]["value"] == 8.0
    # One failed scrape: chips() is empty this tick, but the per-chip
    # series are still within lookback and must stay gpu-labeled.
    flaky.fail = True
    asyncio.run(sampler.tick_fast())
    assert sampler.chips() == []
    out = sampler.query.instant(
        'count(chip.mxu{accel="gpu"})', at=time.time())
    assert out["result"][0]["value"] == 8.0, out
