"""Columnar history engine (ISSUE 5): golden parity with the
pre-tentpole tuple-deque implementation, snapshot-format compatibility
(v1 JSON fixture restores; corrupt v2 refuses cleanly), the
``?series=`` filter, the resample memo, the snapshotter's idle-skip,
and the bounded per-chip recording path."""

from __future__ import annotations

import asyncio
import bisect
import json
import os
import time
from collections import deque
from dataclasses import dataclass, field

import pytest

from tpumon import tsdb
from tpumon.config import load_config
from tpumon.events import EventJournal
from tpumon.history import (
    PROM_QUERIES,
    HistoryService,
    HistorySnapshotter,
    RingHistory,
    RingSeries,
    format_label,
)
from tpumon.sampler import Sampler

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


# ------------------- the legacy (deque) implementation -----------------
# Verbatim copy of the pre-tentpole RingSeries: the golden reference the
# columnar engine must match point-for-point at fine resolution and
# shape-for-shape everywhere.


@dataclass
class LegacyRingSeries:
    window_s: float
    long_window_s: float = 0.0
    coarse_step_s: float = 60.0
    points: deque = field(default_factory=deque)
    coarse: deque = field(default_factory=deque)
    _bucket: int | None = field(default=None, repr=False)
    _bucket_sum: float = field(default=0.0, repr=False)
    _bucket_n: int = field(default=0, repr=False)

    def add(self, ts, value):
        self.points.append((ts, value))
        cutoff = ts - self.window_s
        while self.points and self.points[0][0] < cutoff:
            self.points.popleft()
        if self.long_window_s > self.window_s:
            b = int(ts // self.coarse_step_s)
            if self._bucket is not None and b != self._bucket:
                self._flush_bucket()
            self._bucket = b
            self._bucket_sum += value
            self._bucket_n += 1
            long_cutoff = ts - self.long_window_s
            while self.coarse and self.coarse[0][0] < long_cutoff:
                self.coarse.popleft()

    def _flush_bucket(self):
        if self._bucket is not None and self._bucket_n:
            mid = (self._bucket + 0.5) * self.coarse_step_s
            self.coarse.append((mid, self._bucket_sum / self._bucket_n))
        self._bucket_sum, self._bucket_n = 0.0, 0

    def _fine_since(self, start):
        out = []
        for p in reversed(self.points):
            if p[0] < start:
                break
            out.append(p)
        out.reverse()
        return out

    def merged_points(self, window_s, end):
        start = end - window_s
        fine = self._fine_since(start)
        fine_start = fine[0][0] if fine else float("inf")
        out = [(t, v) for t, v in self.coarse if start <= t < fine_start]
        if self._bucket is not None and self._bucket_n:
            mid = (self._bucket + 0.5) * self.coarse_step_s
            if start <= mid < fine_start:
                out.append((mid, self._bucket_sum / self._bucket_n))
        out.extend(fine)
        return out

    def resample(self, step_s, end=None, window_s=None):
        window_s = window_s if window_s is not None else self.window_s
        if end is None:
            last_fine = self.points[-1][0] if self.points else None
            last_coarse = self.coarse[-1][0] if self.coarse else None
            candidates = [t for t in (last_fine, last_coarse) if t is not None]
            if not candidates:
                return [], []
            end = max(candidates)
        pts = (
            self.merged_points(window_s, end)
            if window_s > self.window_s
            else self._fine_since(end - window_s)
        )
        if not pts:
            return [], []
        start = max(pts[0][0], end - window_s)
        times = [t for t, _ in pts]
        grid, vals = [], []
        t = start
        while t <= end + 1e-9:
            i = bisect.bisect_right(times, t) - 1
            if i >= 0:
                grid.append(t)
                vals.append(pts[i][1])
            t += step_s
        if grid and end - grid[-1] > 1e-9:
            grid.append(end)
            vals.append(pts[-1][1])
        return grid, vals


def legacy_snapshot(s: LegacyRingSeries, step_s, window_s) -> dict:
    grid, vals = s.resample(step_s, window_s=window_s)
    return {
        "labels": [format_label(t, window_s) for t in grid],
        "data": [round(v, 2) for v in vals],
    }


# ----------------------------- golden parity ---------------------------


def feed_both(mid=False, hours=26, step=1.0):
    """Identical 1 Hz-ish stream into a legacy series and a columnar
    one (values 2-decimal, percent-scale: round(f32, 2) is exact)."""
    legacy = LegacyRingSeries(window_s=1800, long_window_s=24 * 3600)
    new = RingSeries(
        window_s=1800,
        long_window_s=24 * 3600,
        coarse_step_s=60.0,
        mid_step_s=30.0 if mid else 0.0,
        mid_window_s=6 * 3600 if mid else 0.0,
    )
    t0 = 1_754_000_000.0
    n = int(hours * 3600 / step)
    for i in range(n):
        ts = t0 + i * step
        v = round(50.0 + 40.0 * ((i % 600) / 600.0), 2)
        legacy.add(ts, v)
        new.add(ts, v)
    return legacy, new


def test_golden_fine_window_identical_to_deque_impl():
    """Acceptance: fine-resolution renders are identical — same labels,
    same point counts, same (rounded) values — to the deque engine."""
    legacy, new = feed_both(mid=True, hours=2)
    for step, window in ((30, 1800.0), (30, 600.0), (30, 120.0)):
        want = legacy_snapshot(legacy, step, window)
        got_grid, got_vals = new.resample(step, window_s=window)
        got = {
            "labels": [format_label(t, window) for t in got_grid],
            "data": [round(v, 2) for v in got_vals],
        }
        assert got["labels"] == want["labels"]
        assert got["data"] == want["data"]


def test_golden_long_windows_same_shape_without_mid_tier():
    """With the mid tier off, the long-window render (coarse + fine
    merge) is also value-identical to the deque engine."""
    legacy, new = feed_both(mid=False, hours=26, step=5.0)
    for window in (3 * 3600.0, 12 * 3600.0, 24 * 3600.0):
        step = max(30.0, round(window / 60.0))
        want = legacy_snapshot(legacy, step, window)
        got_grid, got_vals = new.resample(step, window_s=window)
        assert [format_label(t, window) for t in got_grid] == want["labels"]
        assert [round(v, 2) for v in got_vals] == want["data"]


def test_golden_long_windows_shape_with_mid_tier():
    """With the mid tier on, long windows render on the SAME grid
    (labels + counts) — values inside the mid span come from 30 s
    means instead of 60 s ones, which is the tier's point."""
    legacy, new = feed_both(mid=True, hours=7, step=5.0)
    for window in (3 * 3600.0, 6 * 3600.0):
        step = max(30.0, round(window / 60.0))
        want = legacy_snapshot(legacy, step, window)
        got_grid, _ = new.resample(step, window_s=window)
        assert [format_label(t, window) for t in got_grid] == want["labels"]


def test_api_history_payload_keys_unchanged():
    """The /api/history contract: every pre-tentpole key present with
    labels/data pairs of equal length, per_chip intact."""
    ring = RingHistory(window_s=1800)
    now = time.time()
    for i in range(20):
        ring.record("cpu", 40.0 + i, ts=now - 600 + i * 30)
        ring.record("chip.h0/chip-0.mxu", 50.0, ts=now - 600 + i * 30)
    out = asyncio.run(HistoryService(ring, prometheus_url=None).snapshot())
    assert out["source"] == "ring"
    for key in PROM_QUERIES:
        assert key in out
        assert len(out[key]["labels"]) == len(out[key]["data"])
    assert out["per_chip"]["h0/chip-0.mxu"]["data"]


# ------------------------- ?series= filter -----------------------------


def make_service():
    ring = RingHistory(window_s=1800)
    now = time.time()
    for i in range(10):
        ts = now - 300 + i * 30
        ring.record("cpu", 10.0 + i, ts=ts)
        ring.record("mxu", 60.0, ts=ts)
        ring.record("chip.h0/chip-0.mxu", 61.0, ts=ts)
        ring.record("chip.h0/chip-1.mxu", 62.0, ts=ts)
    return HistoryService(ring, prometheus_url=None)


def test_series_glob_filters_fleet_and_per_chip():
    svc = make_service()
    out = svc.snapshot_ring(series="chip.*")
    assert out["series"] == "chip.*"
    assert "cpu" not in out and "mxu" not in out
    assert set(out["per_chip"]) == {"h0/chip-0.mxu", "h0/chip-1.mxu"}
    one = svc.snapshot_ring(series="chip.h0/chip-0.*")
    assert set(one["per_chip"]) == {"h0/chip-0.mxu"}
    fleet = svc.snapshot_ring(series="cpu")
    assert fleet["cpu"]["data"] and "per_chip" not in fleet
    # No filter: everything, and no "series" echo key (exact old shape).
    full = svc.snapshot_ring()
    assert "series" not in full and "cpu" in full and "per_chip" in full


def test_series_param_served_and_validated_by_route():
    from tests.test_server_api import serve

    sampler, server = serve()
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(sampler.tick_all())
        loop.run_until_complete(sampler.tick_all())
        status, _, body, _ = loop.run_until_complete(
            server.handle_ex("GET", "/api/history", query="series=chip.*")
        )
        assert status == 200
        d = json.loads(body)
        assert "cpu" not in d and d["per_chip"]
        assert all(k.endswith((".mxu", ".hbm", ".temp", ".link"))
                   for k in d["per_chip"])
        from tpumon.server import HttpError

        with pytest.raises(HttpError) as err:
            loop.run_until_complete(
                server.handle_ex(
                    "GET", "/api/history", query="series=%0abad%20glob!"
                )
            )
        assert err.value.status == 400
    finally:
        loop.close()


# --------------------------- resample memo -----------------------------


def test_snapshot_series_memoized_until_series_moves():
    ring = RingHistory(window_s=1800)
    ring.record("cpu", 50.0, ts=1000.0)
    ring.record("mxu", 60.0, ts=1000.0)
    a = ring.snapshot_series("cpu", 30)
    assert ring.snapshot_series("cpu", 30) is a  # memo hit: same object
    ring.record("mxu", 61.0, ts=1030.0)  # another series moving...
    assert ring.snapshot_series("cpu", 30) is a  # ...doesn't invalidate
    ring.record("cpu", 51.0, ts=1030.0)
    b = ring.snapshot_series("cpu", 30)
    assert b is not a and b["data"][-1] == 51.0
    # Distinct windows are distinct memo entries.
    assert ring.snapshot_series("cpu", 30, window_s=600.0) is not b


# ----------------------- snapshot compatibility ------------------------


def shifted_v1_fixture(tmp_path) -> str:
    """The checked-in pre-tentpole v1 JSON snapshot, time-shifted so
    its points land inside the live windows (the file shape is exactly
    what the old code wrote)."""
    with open(os.path.join(FIXTURES, "history_snapshot_v1.json")) as f:
        state = json.load(f)
    delta = time.time() - state["saved_at"]
    state["saved_at"] += delta
    for table in ("points", "coarse"):
        for pts in state[table].values():
            for p in pts:
                p[0] += delta
    path = str(tmp_path / "v1.json")
    with open(path, "w") as f:
        json.dump(state, f)
    return path


def test_v1_json_fixture_restores_into_columnar_store(tmp_path):
    path = shifted_v1_fixture(tmp_path)
    ring = RingHistory(window_s=1800, long_window_s=24 * 3600)
    journal = EventJournal(64)
    snap = HistorySnapshotter(ring, path, journal=journal)
    assert snap.restore()
    # Values and the cursor high-water (newest point) are intact.
    cpu = ring.series["cpu"]
    assert [v for _, v in cpu.points] == [40.0 + i for i in range(30)]
    assert cpu.points[-1][0] == pytest.approx(time.time() - 30 * 30 + 29 * 30, abs=5)
    assert ring.series["chip.host-0/chip-0.mxu"].points[-1][1] == 61.25
    # Old coarse entries survive ahead of the replayed fine span.
    assert list(cpu.coarse)[0][1] == 33.0
    assert any(e["kind"] == "history" for e in journal.recent(10))
    # And the restored store round-trips through the NEW binary format.
    out = str(tmp_path / "v2.bin")
    assert HistorySnapshotter(ring, out).save()
    fresh = RingHistory(window_s=1800, long_window_s=24 * 3600)
    assert HistorySnapshotter(fresh, out).restore()
    assert [v for _, v in fresh.series["cpu"].points] == [
        v for _, v in cpu.points
    ]


def test_binary_roundtrip_preserves_all_tiers(tmp_path):
    ring = RingHistory(window_s=600, long_window_s=24 * 3600)
    # Stream ends slightly in the future so the restore's retention
    # pass (cut against wall-clock now) can't outrun the writer's own
    # eviction bound and drop boundary points mid-test.
    now = time.time() + 30
    for i in range(2000):
        ring.record("cpu", round(30.0 + (i % 50) * 0.5, 2), ts=now - 8000 + i * 4)
    path = str(tmp_path / "hist.bin")
    assert HistorySnapshotter(ring, path).save()
    fresh = RingHistory(window_s=600, long_window_s=24 * 3600)
    assert HistorySnapshotter(fresh, path).restore()
    a, b = ring.series["cpu"], fresh.series["cpu"]
    assert list(a.points) == list(b.points)
    assert list(a.coarse) == list(b.coarse)
    # Renders (incl. mid-tier-backed long windows) identical.
    assert a.resample(30, window_s=7200.0) == b.resample(30, window_s=7200.0)


def test_corrupt_or_truncated_binary_refuses_cleanly(tmp_path):
    ring = RingHistory(window_s=1800)
    now = time.time()
    for i in range(500):
        ring.record("cpu", float(i % 9), ts=now - 500 + i)
    path = str(tmp_path / "hist.bin")
    assert HistorySnapshotter(ring, path).save()
    with open(path, "rb") as f:
        blob = f.read()
    for bad in (blob[: len(blob) // 2], blob[:-3], blob[: len(tsdb.MAGIC) + 2]):
        p = str(tmp_path / "bad.bin")
        with open(p, "wb") as f:
            f.write(bad)
        fresh = RingHistory(window_s=1800)
        journal = EventJournal(64)
        snap = HistorySnapshotter(fresh, p, journal=journal)
        assert not snap.restore()  # refused, not raised
        assert fresh.series == {}  # ring untouched (fresh start)
        assert snap.last_error
        events = journal.recent(5)
        assert any(
            e["kind"] == "history" and e["severity"] == "serious" for e in events
        )


def test_snapshotter_skips_idle_saves_and_health_reports_it(tmp_path):
    ring = RingHistory(window_s=1800)
    ring.record("cpu", 1.0, ts=time.time())
    path = str(tmp_path / "h.bin")
    snap = HistorySnapshotter(ring, path)

    async def run():
        assert await snap.save_async()  # first: dirty -> writes
        assert await snap.save_async()  # unchanged -> skipped
        assert await snap.save_async()
        ring.record("cpu", 2.0, ts=time.time())
        assert await snap.save_async()  # dirty again -> writes

    asyncio.run(run())
    assert snap.saves == 2 and snap.skipped_unchanged == 2
    j = snap.to_json()
    assert j["saves"] == 2 and j["skipped_unchanged"] == 2
    assert j["format"] == "binary"


def test_health_route_exposes_snapshotter_and_history_stats():
    from tests.test_server_api import serve

    sampler, server = serve()
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(sampler.tick_all())
        snap = HistorySnapshotter(sampler.history, "/tmp/unused.bin")
        server.snapshotter = snap  # what app.run wires
        status, _, body, _ = loop.run_until_complete(
            server.handle_ex("GET", "/api/health")
        )
        assert status == 200
        h = json.loads(body)
        assert h["history_snapshot"]["format"] == "binary"
        hist = h["history"]
        assert hist["series"] > 0 and hist["resident_bytes"] > 0
        assert hist["per_chip_cap"] == 256
        assert hist["per_chip_tracked"] == 8  # fake v5e-8
    finally:
        loop.close()


# ------------------------ per-chip gating ------------------------------


def perchip_sampler(cap: int) -> Sampler:
    from tpumon.collectors.accel_fake import FakeTpuCollector

    cfg = load_config(
        env={
            "TPUMON_COLLECTORS": "accel",
            "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
            "TPUMON_HISTORY_PER_CHIP": str(cap),
        }
    )
    return Sampler(cfg, accel=FakeTpuCollector(topology="v5e-8"))


def test_per_chip_cap_bounds_series_and_counts_skips():
    sampler = perchip_sampler(cap=2)
    asyncio.run(sampler.tick_fast())
    asyncio.run(sampler.tick_fast())
    chip_series = {n for n in sampler.history.series if n.startswith("chip.")}
    chips = {n.split(".")[1] for n in chip_series}
    assert len(chips) == 2  # bounded
    assert len(sampler._perchip_skipped) == 6
    h = sampler.health_json()["history"]
    assert h["per_chip_tracked"] == 2 and h["per_chip_skipped"] == 6
    # Tracked set is stable across ticks (first seen wins).
    asyncio.run(sampler.tick_fast())
    assert {n.split(".")[1] for n in sampler.history.series
            if n.startswith("chip.")} == chips


def test_per_chip_zero_disables_and_temp_series_recorded():
    off = perchip_sampler(cap=0)
    asyncio.run(off.tick_fast())
    assert not any(n.startswith("chip.") for n in off.history.series)
    on = perchip_sampler(cap=256)
    asyncio.run(on.tick_fast())
    suffixes = {n.rsplit(".", 1)[1] for n in on.history.series
                if n.startswith("chip.")}
    assert {"mxu", "hbm", "temp"} <= suffixes
