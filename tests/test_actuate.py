"""Actuation engine unit tests (tpumon/actuate.py, docs/actuation.md):
spec parsing/rejection, the guarded state machine (fire/clear holds,
cooldown, global rate limit), dry-run state-freeze, shed-cap clamping,
drain bookkeeping — and the ServingEngine actuation surface (shed
pacing determinism, the distinct `shed` terminal status staying OUT of
the collector's per-tenant error rate, live capacity nudges, and
drain-and-requeue's stream/TTFT invariants). The closed loop over a
live monitor is tests/test_actuate_soak.py."""

import jax  # noqa: F401  (device bring-up before the engine tests)

from tpumon.actuate import (
    ActuationEngine,
    ActuationSpec,
    EngineActuator,
    parse_actuations,
)
from tpumon.collectors.serving import distill_serving_metrics
from tpumon.events import EventJournal
from tpumon.history import RingHistory
from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import SHED_CAP, ServeConfig, ServingEngine
from tpumon.query import QueryEngine

CFG = ServeConfig(
    model=ModelConfig(vocab=97, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq=32,
                      compute_dtype="float32"),
    slots=2, prefill_len=8,
)

T0 = 1_700_000_000.0


# ------------------------------ spec parsing ------------------------------


def test_parse_rejects_bad_specs_keeps_good_ones():
    specs, errors = parse_actuations([
        {"name": "ok", "when": "cpu > 90", "action": "shed"},
        {"name": "bad.dot", "when": "cpu > 90", "action": "shed"},
        {"name": "noexpr", "when": "cpu >", "action": "shed"},
        {"name": "what", "when": "cpu > 90", "action": "scale_the_moon"},
        {"name": "frac", "when": "cpu > 90", "action": "shed",
         "fraction": 1.5},
        {"name": "keys", "when": "cpu > 90", "action": "shed",
         "prefill_budget": 2},  # capacity key on a shed action
        {"name": "cap0", "when": "cpu > 90", "action": "capacity"},
        "not-a-dict",
    ])
    assert [s.name for s in specs] == ["ok"]
    assert len(errors) == 7
    joined = " ".join(errors)
    for frag in ("bad.dot", "noexpr", "scale_the_moon", "fraction",
                 "unknown keys", "prefill_budget"):
        assert frag in joined, (frag, errors)


def test_parse_rejects_duplicate_names():
    specs, errors = parse_actuations([
        {"name": "p", "when": "cpu > 1", "action": "shed"},
        {"name": "p", "when": "cpu > 2", "action": "drain"},
    ])
    assert specs == []
    assert any("duplicate" in e for e in errors)


def test_spec_defaults_and_duration_cooldown():
    spec = ActuationSpec.parse(
        {"name": "p", "when": "cpu > 1", "action": "shed",
         "cooldown_s": "1m"})
    assert spec.cooldown_s == 60.0
    assert spec.fire_hold == 2 and spec.clear_hold == 2
    assert spec.tenant == "*" and spec.fraction == 0.25


# --------------------------- state-machine rig ---------------------------


class RecordingActuator:
    """Records every verb; capacity() serves a fixed baseline."""

    def __init__(self):
        self.calls = []

    def shed(self, tenant, fraction):
        self.calls.append(("shed", tenant, round(fraction, 4)))
        return fraction

    def unshed(self, tenant):
        self.calls.append(("unshed", tenant))

    def capacity(self):
        return {"prefill_budget": 1, "admit_lookahead": 0}

    def nudge(self, prefill_budget=None, admit_lookahead=None):
        self.calls.append(("nudge", prefill_budget, admit_lookahead))
        return {"prefill_budget": prefill_budget or 1,
                "admit_lookahead": 0 if admit_lookahead is None
                else admit_lookahead}

    def drain(self, s):
        self.calls.append(("drain", s))

    def undrain(self, s):
        self.calls.append(("undrain", s))


def rig(raw_specs, **kw):
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, errors = parse_actuations(raw_specs)
    assert not errors, errors
    act = RecordingActuator()
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal,
                          actuator=act, **kw)
    return eng, ring, journal, act


def feed(ring, name, value, ts):
    ring.record_batch([(ring.handle(name), value)], ts=ts)


def states(journal):
    return [e.get("state") for e in journal.after(0, kind="actuate")]


# ------------------------- hysteresis / cooldown -------------------------


def test_fire_and_clear_holds():
    eng, ring, journal, act = rig([{
        "name": "p", "when": "cpu > 90", "action": "shed", "tenant": "t",
        "fraction": 0.2, "cooldown_s": 0, "fire_hold": 3, "clear_hold": 2,
    }])
    pol = eng.policies[0]
    # Two hot ticks: armed but held (fire_hold 3).
    for i in range(2):
        feed(ring, "cpu", 95.0, T0 + i)
        eng.observe(T0 + i)
    assert pol.state == "armed" and act.calls == []
    # A cool tick resets the hold entirely.
    feed(ring, "cpu", 10.0, T0 + 2)
    eng.observe(T0 + 2)
    assert pol.state == "idle"
    # Three consecutive hot ticks fire.
    for i in range(3, 6):
        feed(ring, "cpu", 95.0, T0 + i)
        eng.observe(T0 + i)
    assert pol.state == "fired"
    assert act.calls == [("shed", "t", 0.2)]
    # One clearing tick holds (clear_hold 2); the second reverts.
    feed(ring, "cpu", 10.0, T0 + 6)
    eng.observe(T0 + 6)
    assert pol.state == "fired"
    feed(ring, "cpu", 10.0, T0 + 7)
    eng.observe(T0 + 7)
    assert pol.state == "idle"
    assert act.calls[-1] == ("unshed", "t")
    # Two arming episodes (the cool tick reset the first), one fire,
    # one revert.
    assert states(journal) == ["armed", "armed", "fired", "reverted"]
    # Journal attrs carry the audit trail: expression + observed value.
    fired = [e for e in journal.after(0, kind="actuate")
             if e["state"] == "fired"][0]
    assert fired["expr"] == "cpu > 90"
    assert fired["value"] == 95.0
    assert fired["policy"] == "p" and fired["action"] == "shed"


def test_cooldown_suppresses_refire_once_per_episode():
    eng, ring, journal, act = rig([{
        "name": "p", "when": "cpu > 90", "action": "shed",
        "cooldown_s": 100.0, "fire_hold": 1, "clear_hold": 1,
    }])
    feed(ring, "cpu", 95.0, T0)
    eng.observe(T0)  # armed
    eng.observe(T0 + 1)  # fired (hold satisfied on the 2nd hot tick)
    feed(ring, "cpu", 10.0, T0 + 2)
    eng.observe(T0 + 2)  # reverted
    # Condition returns inside the cooldown: suppressed, ONCE, for the
    # whole armed episode — not one journal event per tick.
    feed(ring, "cpu", 95.0, T0 + 3)
    for i in range(3, 8):
        eng.observe(T0 + i)
    assert eng.policies[0].suppressed == 1
    assert states(journal).count("suppressed") == 1
    assert len([c for c in act.calls if c[0] == "shed"]) == 1
    # Past the cooldown the held policy finally fires.
    eng.observe(T0 + 102)
    assert eng.policies[0].state == "fired"
    assert len([c for c in act.calls if c[0] == "shed"]) == 2


def test_global_rate_limit_blocks_and_never_blocks_reverts():
    eng, ring, journal, act = rig(
        [
            {"name": "a", "when": "cpu > 90", "action": "shed",
             "tenant": "a", "cooldown_s": 0, "fire_hold": 1,
             "clear_hold": 1},
            {"name": "b", "when": "cpu > 90", "action": "shed",
             "tenant": "b", "cooldown_s": 0, "fire_hold": 1,
             "clear_hold": 1},
        ],
        max_actions=1, window_s=1000.0,
    )
    feed(ring, "cpu", 95.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    by_name = {p.spec.name: p for p in eng.policies}
    # Budget 1: exactly one policy fired, the other was rate-limited.
    assert sorted(p.state for p in eng.policies) == ["armed", "fired"]
    limited = [p for p in eng.policies if p.state == "armed"][0]
    assert limited.rate_limited == 1
    assert "rate-limited" in states(journal)
    # The fired policy's revert goes through even with the budget spent.
    feed(ring, "cpu", 10.0, T0 + 2)
    eng.observe(T0 + 2)
    assert by_name["a"].state == "idle" or by_name["b"].state == "idle"
    assert any(c[0] == "unshed" for c in act.calls)
    assert eng.to_json()["actions_in_window"] == 1
    assert eng.to_json()["max_actions"] == 1
    assert eng.to_json()["window_s"] == 1000.0


def test_shed_fraction_clamped_to_engine_cap():
    eng, ring, journal, act = rig(
        [{"name": "p", "when": "cpu > 90", "action": "shed",
          "fraction": 0.9, "cooldown_s": 0, "fire_hold": 1,
          "clear_hold": 1}],
        shed_max_fraction=0.35,
    )
    feed(ring, "cpu", 95.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    assert act.calls == [("shed", "*", 0.35)]


def test_overlapping_shed_policies_combine_and_relax():
    """Two shed policies on the SAME tenant: the engine holds one
    fraction per tenant, so the actuation layer must combine (shed at
    the max of every fired policy) and a revert must relax to the
    remaining max — never remove the throttle out from under a policy
    that is still fired."""
    eng, ring, journal, act = rig([
        {"name": "mild", "when": "slow_burn > 0", "action": "shed",
         "tenant": "chat", "fraction": 0.25, "cooldown_s": 0,
         "fire_hold": 1, "clear_hold": 1},
        {"name": "hard", "when": "fast_burn > 0", "action": "shed",
         "tenant": "chat", "fraction": 0.6, "cooldown_s": 0,
         "fire_hold": 1, "clear_hold": 1},
    ], shed_max_fraction=0.75)
    # Both conditions hold; both policies fire.
    feed(ring, "slow_burn", 1.0, T0)
    feed(ring, "fast_burn", 1.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    assert [c for c in act.calls if c[0] == "shed"] == [
        ("shed", "chat", 0.25), ("shed", "chat", 0.6)]
    # The aggressive policy clears first: the tenant RELAXES to the
    # mild policy's 0.25, it is not unshed.
    feed(ring, "fast_burn", 0.0, T0 + 2)
    eng.observe(T0 + 2)
    by_name = {p.spec.name: p for p in eng.policies}
    assert by_name["hard"].state == "idle"
    assert by_name["mild"].state == "fired"
    assert act.calls[-1] == ("shed", "chat", 0.25)
    assert "relaxed to 0.25" in by_name["hard"].last
    # The mild policy clears last: only now is the throttle removed.
    feed(ring, "slow_burn", 0.0, T0 + 3)
    eng.observe(T0 + 3)
    assert act.calls[-1] == ("unshed", "chat")


class StatefulCapacityActuator(RecordingActuator):
    """capacity() reflects live nudges — the shape a real engine has,
    and what the overlapping-capacity regression needs (a fixed
    baseline would mask a later policy capturing an earlier policy's
    nudged values as its revert target)."""

    def __init__(self):
        super().__init__()
        self.state = {"prefill_budget": 1, "admit_lookahead": 0}

    def capacity(self):
        return dict(self.state)

    def nudge(self, prefill_budget=None, admit_lookahead=None):
        self.calls.append(("nudge", prefill_budget, admit_lookahead))
        if prefill_budget is not None:
            self.state["prefill_budget"] = prefill_budget
        if admit_lookahead is not None:
            self.state["admit_lookahead"] = admit_lookahead
        return dict(self.state)


def test_overlapping_capacity_policies_share_true_baseline():
    """Two capacity policies fired together must not corrupt each
    other's revert target: the TRUE pre-actuation baseline is captured
    once (at the first fire — a later policy reading capacity() live
    would capture the first one's nudge), one policy's revert re-layers
    the still-fired policies' nudges, and the last revert restores the
    real baseline."""
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, errors = parse_actuations([
        {"name": "a", "when": "a_sig > 0", "action": "capacity",
         "prefill_budget": 2, "cooldown_s": 0, "fire_hold": 1,
         "clear_hold": 1},
        {"name": "b", "when": "b_sig > 0", "action": "capacity",
         "prefill_budget": 4, "cooldown_s": 0, "fire_hold": 1,
         "clear_hold": 1},
    ])
    assert not errors
    act = StatefulCapacityActuator()
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal,
                          actuator=act)
    by_name = {p.spec.name: p for p in eng.policies}
    # a fires first (budget 1 -> 2), then b (2 -> 4).
    feed(ring, "a_sig", 1.0, T0)
    feed(ring, "b_sig", 0.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    assert by_name["a"].state == "fired"
    assert act.state["prefill_budget"] == 2
    feed(ring, "b_sig", 1.0, T0 + 2)
    eng.observe(T0 + 2)
    eng.observe(T0 + 3)
    assert by_name["b"].state == "fired"
    assert act.state["prefill_budget"] == 4
    # a clears while b is still fired: b's nudge survives — the engine
    # restores the baseline then re-layers b, never parking capacity at
    # a's pre-fire value out from under b.
    feed(ring, "a_sig", 0.0, T0 + 4)
    eng.observe(T0 + 4)
    assert by_name["a"].state == "idle" and by_name["b"].state == "fired"
    assert act.state["prefill_budget"] == 4
    assert "re-layered" in by_name["a"].last
    # b clears last: the TRUE baseline (1, not a's nudged 2) returns.
    feed(ring, "b_sig", 0.0, T0 + 5)
    eng.observe(T0 + 5)
    assert by_name["b"].state == "idle"
    assert act.state == {"prefill_budget": 1, "admit_lookahead": 0}


def test_overlapping_drain_policies_refcount_slices():
    """A slice drained by two fired policies stays drained until the
    LAST one reverts — one policy's clear must not undrain a slice
    another still-fired policy is holding dark."""
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, errors = parse_actuations([
        {"name": "a", "when": "a_sig > 0", "action": "drain",
         "slice": "sX", "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1},
        {"name": "b", "when": "b_sig > 0", "action": "drain",
         "slice": "sX", "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1},
    ])
    assert not errors
    act = RecordingActuator()
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal,
                          actuator=act)
    by_name = {p.spec.name: p for p in eng.policies}
    feed(ring, "a_sig", 1.0, T0)
    feed(ring, "b_sig", 1.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    assert by_name["a"].state == "fired" and by_name["b"].state == "fired"
    # Drained once, not per policy (the hold is refcounted).
    assert act.calls.count(("drain", "sX")) == 1
    # a reverts while b still holds the slice: NO undrain.
    feed(ring, "a_sig", 0.0, T0 + 2)
    eng.observe(T0 + 2)
    assert by_name["a"].state == "idle" and by_name["b"].state == "fired"
    assert ("undrain", "sX") not in act.calls
    assert "still drained by other policies: sX" in by_name["a"].last
    # b reverts last: now the slice undrains, exactly once.
    feed(ring, "b_sig", 0.0, T0 + 3)
    eng.observe(T0 + 3)
    assert act.calls.count(("undrain", "sX")) == 1


def test_capacity_reverts_to_prefire_baseline():
    eng, ring, journal, act = rig([{
        "name": "cap", "when": "avg_over_time(queue_depth[30s]) > 8",
        "action": "capacity", "prefill_budget": 4, "admit_lookahead": 4,
        "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1,
    }])
    # The trend window rides a recording rule, never a point walk.
    assert eng.rule_texts() == ["queue_depth[30s]"]
    for i in range(3):
        feed(ring, "queue_depth", 20.0, T0 + i)
        eng.observe(T0 + i)
    assert eng.policies[0].state == "fired"
    assert ("nudge", 4, 4) in act.calls
    for i in range(3, 40):
        feed(ring, "queue_depth", 0.0, T0 + i)
        eng.observe(T0 + i)
    assert eng.policies[0].state == "idle"
    # Revert nudged back to the captured baseline, not a hardcoded one.
    assert act.calls[-1] == ("nudge", 1, 0)


def test_drain_targets_current_darks_and_reverts_exactly_those():
    darks = ["s1", "s3"]
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, errors = parse_actuations([{
        "name": "d", "when": "federation.dark > 0", "action": "drain",
        "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1,
    }])
    assert not errors
    act = RecordingActuator()
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal,
                          actuator=act, dark_slices=lambda: list(darks))
    eng.observe(T0)  # records federation.dark=2, arms
    eng.observe(T0 + 1)
    assert act.calls == [("drain", "s1"), ("drain", "s3")]
    # Recovery: darks empty -> condition clears -> undrain the SAME set
    # (even though nothing is dark NOW — the fired set is remembered).
    darks.clear()
    eng.observe(T0 + 2)
    assert eng.policies[0].state == "idle"
    assert act.calls[-2:] == [("undrain", "s1"), ("undrain", "s3")]
    # A None provider result means "no fleet here" (standalone
    # monitor): the per-tick federation.dark record is skipped
    # entirely, not written as 0.0.
    ring2 = RingHistory(window_s=600)
    eng2 = ActuationEngine(specs, QueryEngine(ring2), ring2,
                           EventJournal(64), actuator=RecordingActuator(),
                           dark_slices=lambda: None)
    eng2.observe(T0)
    assert "federation.dark" not in ring2.series


def test_fired_policy_with_explicit_clear_reverts_on_vanished_data():
    """A fired policy whose explicit `clear` expression reads NO data
    at all (collector died, source drained) must revert through the
    normal clear_hold — not wedge fired forever because absent maps to
    False for both expressions. Same staleness class slo.py hardens;
    the safe direction for a remedy is revert."""
    eng, ring, journal, act = rig([{
        "name": "p", "when": "avg_over_time(sig[30s]) > 5",
        "clear": "avg_over_time(sig[30s]) < 2", "action": "shed",
        "tenant": "t", "cooldown_s": 0, "fire_hold": 1, "clear_hold": 2,
    }])
    pol = eng.policies[0]
    feed(ring, "sig", 10.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    assert pol.state == "fired"
    # Present-but-not-clearing data holds the remedy (8 is neither > 5
    # after the window drains below... keep it simple: still > 5).
    feed(ring, "sig", 10.0, T0 + 2)
    eng.observe(T0 + 2)
    assert pol.state == "fired"
    # The series vanishes: 90s later every window read is empty. Two
    # absent ticks (clear_hold 2) revert instead of wedging.
    eng.observe(T0 + 95)
    assert pol.state == "fired" and pol.clear_count == 1
    eng.observe(T0 + 96)
    assert pol.state == "idle"
    assert act.calls[-1] == ("unshed", "t")


def test_rule_texts_register_matcher_carrying_selectors():
    """A per-tenant trend condition must ride a recording rule like a
    bare one: rules are per-family with per-matched-series state, so
    `{tenant="chat"}` reads are rule-served too — skipping them would
    send the condition to a per-tick point walk."""
    ring = RingHistory(window_s=600)
    specs, errors = parse_actuations([{
        "name": "p",
        "when": 'avg_over_time(serving.ttft_p95_ms{tenant="chat"}[5m])'
                ' > 500',
        "action": "shed", "tenant": "chat",
    }])
    assert not errors
    eng = ActuationEngine(specs, QueryEngine(ring), ring,
                          EventJournal(64))
    assert eng.rule_texts() == ["serving.ttft_p95_ms[300s]"]


def test_dark_provider_not_called_without_dark_reading_policies():
    """A shed/capacity-only policy set must not pay the per-tick
    hub.slices() walk or the federation.dark TSDB append — the
    provider is not even called unless a drain policy or a
    federation.dark condition exists."""
    calls = []

    def provider():
        calls.append(1)
        return ["s1"]

    ring = RingHistory(window_s=600)
    specs, _ = parse_actuations([{
        "name": "p", "when": "cpu > 90", "action": "shed"}])
    eng = ActuationEngine(specs, QueryEngine(ring), ring,
                          EventJournal(64), actuator=RecordingActuator(),
                          dark_slices=provider)
    eng.observe(T0)
    assert calls == [] and "federation.dark" not in ring.series
    # A drain policy (or a federation.dark condition) flips it on.
    specs2, _ = parse_actuations([{
        "name": "d", "when": "federation.dark > 0", "action": "drain"}])
    eng2 = ActuationEngine(specs2, QueryEngine(ring), ring,
                           EventJournal(64), actuator=RecordingActuator(),
                           dark_slices=provider)
    eng2.observe(T0)
    assert calls == [1] and "federation.dark" in ring.series


def test_placement_domains_synced_into_engine_before_any_fire():
    """The drain family's production wiring: the policy engine keeps
    the serving engine's placement-domain namespace synced to the
    fleet's (set_slices), so requests carry a slice attribution BEFORE
    a drain ever fires — without it the drain verbs journal success
    while nothing is ever attributed, aborted, or requeued."""
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, _ = parse_actuations([{
        "name": "d", "when": "federation.dark > 0", "action": "drain",
        "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1}])
    serving = ServingEngine(cfg=CFG)
    domains = ["s1", "s0"]
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal,
                          dark_slices=lambda: [],
                          placement_domains=lambda: list(domains))
    eng.bind_engine(serving)
    eng.observe(T0)
    # Synced (sorted) with NO policy fired — attribution is the
    # prerequisite, not the remedy.
    assert serving.slices == ("s0", "s1")
    r = serving.submit([1, 2, 3], max_new=1)
    serving.drain()
    assert r.slice in ("s0", "s1")
    # A domain appears: re-synced. An empty read (fleet view warming
    # up) keeps the last known namespace.
    domains.append("s2")
    eng.observe(T0 + 1)
    assert serving.slices == ("s0", "s1", "s2")
    domains.clear()
    eng.observe(T0 + 2)
    assert serving.slices == ("s0", "s1", "s2")
    assert any(e.get("state") == "domains"
               for e in journal.after(0, kind="actuate"))
    # Dry-run drain policies sync nothing (engine state frozen).
    specs_dry, _ = parse_actuations([{
        "name": "d", "when": "federation.dark > 0", "action": "drain",
        "dry_run": True}])
    serving2 = ServingEngine(cfg=CFG)
    eng2 = ActuationEngine(specs_dry, QueryEngine(ring), ring,
                           EventJournal(64), dark_slices=lambda: [],
                           placement_domains=lambda: ["s0"])
    eng2.bind_engine(serving2)
    eng2.observe(T0)
    assert serving2.slices == ()


# -------------------------------- dry-run --------------------------------


def test_dry_run_journals_intent_but_freezes_engine_state():
    """The acceptance wording: a dry-run policy journals intent but
    provably changes no engine state — asserted against a REAL
    ServingEngine behind the real EngineActuator."""
    ring = RingHistory(window_s=600)
    journal = EventJournal(512)
    specs, _ = parse_actuations([{
        "name": "p", "when": "cpu > 90", "action": "shed",
        "tenant": "chat", "cooldown_s": 0, "fire_hold": 1,
        "clear_hold": 1, "dry_run": True,
    }])
    serving = ServingEngine(cfg=CFG)
    eng = ActuationEngine(specs, QueryEngine(ring), ring, journal)
    eng.bind_engine(serving)
    assert isinstance(eng.actuator, EngineActuator)
    feed(ring, "cpu", 95.0, T0)
    eng.observe(T0)
    eng.observe(T0 + 1)
    fired = [e for e in journal.after(0, kind="actuate")
             if e.get("state") == "fired"]
    assert len(fired) == 1 and fired[0]["dry_run"] is True
    assert "(dry-run)" in fired[0]["msg"]
    # Intent reads like the live action would...
    assert "shed tenant chat" in fired[0]["msg"]
    # ...but nothing reached the engine.
    assert serving.shed_fractions() == {}
    assert serving.shed_total == 0
    # Dry-run fires never consume the global action budget.
    assert eng.to_json()["actions_in_window"] == 0
    row = eng.to_json()["policies"][0]
    assert row["dry_run"] is True and row["fired"] == 1
    # Unbound engines are implicitly dry (intent-only), surfaced on the
    # payload the dashboard card badges.
    unbound = ActuationEngine(specs, QueryEngine(ring), ring,
                              EventJournal(64))
    unbound.observe(T0)
    assert unbound.to_json()["engine_bound"] is False


def test_slo_paging_series_gated_on_actuation():
    """slo.<name>.paging exists FOR actuation conditions: an SLOEngine
    with record_paging off (the default — the sampler flips it on only
    when policies are configured) must not pay a per-objective TSDB
    append every tick for a series nothing reads."""
    from tpumon.slo import SLOEngine, parse_slos

    ring = RingHistory(window_s=600)
    q = QueryEngine(ring)
    specs, errors = parse_slos([
        {"name": "chat_ttft", "expr": "ttft > 100", "target": 0.99,
         "window": "1h"}])
    assert not errors
    eng = SLOEngine(specs, q, ring, EventJournal(64))
    feed(ring, "ttft", 50.0, T0)
    eng.observe(T0)
    assert not any(s.endswith(".paging") for s in ring.series)
    eng.record_paging = True
    eng.observe(T0 + 1)
    assert "slo.chat_ttft.paging" in ring.series


# --------------------------- payload / exporter ---------------------------


def test_payload_shape_and_exporter_rows():
    eng, ring, journal, act = rig([{
        "name": "p", "when": "cpu > 90", "action": "shed",
        "cooldown_s": 0, "fire_hold": 1, "clear_hold": 1,
    }])
    changed = eng.observe(T0)
    assert changed  # first publish
    assert eng.observe(T0 + 1) is False  # idle, nothing moved
    out = eng.to_json()
    assert out["evaluated_at"] == T0 + 1
    row = out["policies"][0]
    for key in ("name", "action", "when", "state", "dry_run", "value",
                "last", "last_ts", "fired", "reverted", "suppressed",
                "rate_limited"):
        assert key in row, key
    # The exporter block renders every tpumon_actuate_* family.
    from tpumon.exporter import _render_actuate

    class S:
        actuate = eng

    text = _render_actuate(S())
    for fam in ("tpumon_actuate_policy_state",
                "tpumon_actuate_policy_dry_run",
                "tpumon_actuate_fired_total",
                "tpumon_actuate_reverted_total",
                "tpumon_actuate_suppressed_total",
                "tpumon_actuate_rate_limited_total",
                "tpumon_actuate_actions_in_window"):
        assert fam in text, fam
    assert 'policy="p"' in text
    assert _render_actuate(type("S2", (), {"actuate": None})()) == ""


# ---------------------- ServingEngine actuation surface ----------------------


def test_engine_shed_pacing_is_deterministic_and_capped():
    eng = ServingEngine(cfg=CFG)
    assert eng.set_shed("chat", 0.5) == 0.5
    reqs = [eng.submit([1, 2, 3], max_new=2, tenant="chat")
            for _ in range(10)]
    shed = [r for r in reqs if r.status == "shed"]
    # fraction 0.5 sheds EXACTLY every 2nd submission — no RNG.
    assert [r.status for r in reqs] == ["", "shed"] * 5
    assert len(shed) == 5 and eng.shed_total == 5
    for r in shed:
        assert r.done.is_set() and not r.output
    eng.drain()
    assert sum(1 for r in reqs if r.status == "completed") == 5
    # Tenant accounting: sheds are their own column, never rejections.
    tst = eng.tenants["chat"]
    assert tst.shed == 5 and tst.rejected == 0
    # Engine-side last-resort cap, then full removal.
    assert eng.set_shed("chat", 2.0) == SHED_CAP
    assert eng.set_shed("chat", 0.0) == 0.0
    assert eng.shed_fractions() == {}
    # "*" sheds tenants without their own entry.
    eng.set_shed("*", 1.0)
    r = eng.submit([1], max_new=1, tenant="other")
    r2 = eng.submit([1], max_new=1, tenant="other")
    assert "shed" in (r.status, r2.status)


def test_shed_accumulator_resets_between_episodes():
    """Removing a shed throttle clears the pacing accumulators it
    drove — a "*" throttle paces under each tenant's OWN name, so the
    next episode must start at a fresh accumulator (deterministic
    pacing is per-episode) and nothing may leak across episodes."""
    eng = ServingEngine(cfg=CFG)
    eng.set_shed("*", 0.5)
    r = eng.submit([1, 2], max_new=1, tenant="chat")  # acc 0.5: passes
    assert r.status == ""
    eng.drain()
    eng.set_shed("*", 0.0)
    assert eng._shed_acc == {}  # the "*"-paced accumulator is gone
    # Fresh episode at 0.9: the FIRST submission accumulates to 0.9
    # (< 1.0) and passes; a stale 0.5 carry-over would shed it.
    eng.set_shed("*", 0.9)
    r2 = eng.submit([1, 2], max_new=1, tenant="chat")
    assert r2.status == ""
    eng.drain()
    # A tenant-specific throttle's accumulator survives "*" removal.
    eng.set_shed("chat", 0.5)
    eng.submit([1, 2], max_new=1, tenant="chat")  # acc under "chat"
    eng.drain()
    eng.set_shed("*", 0.0)
    assert "chat" in eng._shed_acc
    eng.set_shed("chat", 0.0)
    assert eng._shed_acc == {}


def test_shed_never_pollutes_tenant_error_rate():
    """The satellite regression: shed at admission must not count
    toward the tenant's error_rate (it would re-fire the SLO that
    triggered the shed) — end to end through the engine's /metrics
    exposition and the serving collector's distillation."""
    eng = ServingEngine(cfg=CFG, max_queue=4)
    for _ in range(3):
        eng.submit([1, 2], max_new=1, tenant="chat")
    eng.drain()
    d0 = distill_serving_metrics(eng.metrics_text(), now=1000.0)
    assert d0["tenants"]["chat"]["shed_total"] == 0
    # Shed half the next window's traffic.
    eng.set_shed("chat", 0.5)
    for _ in range(8):
        eng.submit([1, 2], max_new=1, tenant="chat")
        eng.drain()  # drain as we go: nothing queues, nothing rejects
    d1 = distill_serving_metrics(eng.metrics_text(), prev=d0, now=1010.0)
    row = d1["tenants"]["chat"]
    assert row["shed_total"] == 4
    assert row["error_rate"] == 0.0  # sheds excluded from BOTH sides
    assert "tpumon_serving_tenant_shed" in eng.metrics_text()
    assert "tpumon_serving_requests_shed" in eng.metrics_text()
    # Contrast: real rejections DO count. Fill the queue past capacity
    # with shedding off.
    eng.set_shed("chat", 0.0)
    for _ in range(12):
        eng.submit([1, 2], max_new=1, tenant="chat")
    eng.drain()
    d2 = distill_serving_metrics(eng.metrics_text(), prev=d1, now=1020.0)
    row2 = d2["tenants"]["chat"]
    assert row2["rejected_total"] > d1["tenants"]["chat"].get(
        "rejected_total", 0)
    assert row2["error_rate"] > 0.0


def test_engine_nudge_capacity_live():
    eng = ServingEngine(cfg=CFG)
    base = eng.nudge_capacity()
    assert base == {"prefill_budget": 1, "admit_lookahead": 0}
    eff = eng.nudge_capacity(prefill_budget=4)
    assert eff["prefill_budget"] == 4
    # The engine still serves correctly with the nudged budget (the
    # knob never reached a trace).
    r = eng.submit([3, 1, 4, 1, 5], max_new=4)
    eng.drain()
    assert r.status == "completed" and len(r.output) == 5
    eng.nudge_capacity(**base)
    assert eng.cfg.prefill_chunk_budget == 1
    # Floors: a nonsense nudge clamps instead of wedging the scheduler.
    assert eng.nudge_capacity(prefill_budget=-3)["prefill_budget"] == 1


def test_drain_and_requeue_stream_and_ttft_invariants():
    """Drain-and-requeue: the aborted request re-admits at the queue
    head, regenerates a bit-identical token prefix (keyed sampling),
    never double-delivers stream tokens, and observes TTFT exactly
    once (on the original admission)."""
    eng = ServingEngine(cfg=CFG)
    eng.set_slices(["s0", "s1"])
    r = eng.submit([5, 6, 7, 8, 9], max_new=6, temperature=0.8,
                   stream=True)
    delivered = []
    for _ in range(200):
        eng.step()
        while not r.stream.empty():
            t = r.stream.get_nowait()
            if t is not None:
                delivered.append(t)
        if len(delivered) >= 2:
            break
    assert r.slice in ("s0", "s1")
    prefix = list(delivered)
    eng.drain_slice(r.slice)
    assert eng.drained_slices() == (("s0",) if prefix and r.slice == "s0"
                                    else eng.drained_slices())
    eng.drain()
    while True:
        t = r.stream.get()
        if t is None:
            break
        delivered.append(t)
    assert r.status == "completed"
    assert r.requeues == 1 and eng.requeued_total == 1
    # Bit-identical prefix across the requeue, and exactly-once stream.
    assert r.output[:len(prefix)] == prefix
    assert delivered == r.output
    # TTFT observed once across both runs.
    assert sum(eng._ttft_counts) + eng._ttft_inf == 1
    assert "tpumon_serving_requests_requeued" in eng.metrics_text()


def test_drained_domain_avoided_until_undrained():
    eng = ServingEngine(cfg=CFG)
    eng.set_slices(["s0", "s1"])
    eng.drain_slice("s0")
    reqs = [eng.submit([i + 1, i + 2], max_new=1) for i in range(4)]
    eng.drain()
    assert all(r.slice == "s1" for r in reqs)
    eng.undrain_slice("s0")
    assert eng.drained_slices() == ()
    reqs2 = [eng.submit([i + 1, i + 2], max_new=1) for i in range(4)]
    eng.drain()
    assert {r.slice for r in reqs2} == {"s0", "s1"}
    # set_slices drops drain marks for renamed domains.
    eng.drain_slice("s1")
    eng.set_slices(["a", "b"])
    assert eng.drained_slices() == ()


def test_all_drained_fallback_then_rehome_on_undrain():
    """With EVERY domain drained, placement falls back (liveness: the
    sweep must not requeue-thrash a request it has nowhere to send);
    the mark persists, so the moment any domain is undrained the
    per-step sweep re-homes the stragglers."""
    eng = ServingEngine(cfg=CFG)
    eng.set_slices(["s0", "s1"])
    eng.drain_slice("s0")
    eng.drain_slice("s1")
    r = eng.submit([7, 8, 9], max_new=8)
    for _ in range(3):
        eng.step()
    # Fallback-parked on a drained domain, NOT requeue-thrashed.
    assert r.slice in ("s0", "s1")
    assert r.requeues == 0 and r.status == ""
    parked = r.slice
    other = "s1" if parked == "s0" else "s0"
    # A domain frees: the persistent mark now re-homes the request.
    eng.undrain_slice(other)
    eng.drain()
    assert r.status == "completed"
    assert r.requeues == 1 and r.slice == other
