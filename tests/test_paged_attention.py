"""Pallas paged-attention kernel vs the dense gather oracle (interpret
mode on CPU; compiles on real TPU like the flash/matmul siblings)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpumon.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
)


def make_case(b=3, nh=4, nkv=2, hd=16, num_pages=12, page_size=8,
              max_pages=4, lengths=(5, 17, 32), seed=0, dtype=jnp.float32):
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(keys[0], (b, nh, hd), dtype)
    k_pages = jax.random.normal(
        keys[1], (nkv, num_pages, page_size, hd), dtype)
    v_pages = jax.random.normal(
        keys[2], (nkv, num_pages, page_size, hd), dtype)
    # Distinct pages per sequence (a real allocator never shares live
    # pages); unused table entries point at page 0 — any valid id.
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_pages)
    table = np.zeros((b, max_pages), np.int32)
    flat = iter(perm)
    for i, n in enumerate(lengths):
        used = -(-n // page_size)  # ceil
        for j in range(used):
            table[i, j] = next(flat)
    return (q, k_pages, v_pages, jnp.asarray(table),
            jnp.asarray(lengths, jnp.int32))


def test_matches_oracle_mixed_lengths():
    case = make_case()
    out = paged_attention(*case, interpret=True)
    ref = paged_attention_reference(*case)
    assert jnp.allclose(out, ref, atol=1e-5), (
        float(jnp.abs(out - ref).max()))


def test_gqa_group_of_four():
    case = make_case(nh=8, nkv=2, lengths=(8, 24, 31))
    out = paged_attention(*case, interpret=True)
    ref = paged_attention_reference(*case)
    assert jnp.allclose(out, ref, atol=1e-5)


def test_single_token_and_full_pages():
    # length 1 (one row of one page) and exactly max_pages*page_size.
    case = make_case(lengths=(1, 32, 16))
    out = paged_attention(*case, interpret=True)
    ref = paged_attention_reference(*case)
    assert jnp.allclose(out, ref, atol=1e-5)


def test_zero_length_sequence_is_zeros():
    case = make_case(lengths=(0, 9, 12))
    out = paged_attention(*case, interpret=True)
    assert jnp.allclose(out[0], 0.0)
    ref = paged_attention_reference(*case)
    assert jnp.allclose(out[1:], ref[1:], atol=1e-5)


def test_page_order_is_table_order():
    """Shuffling page ids while shuffling pool contents to match must
    not change the result — the table is the source of truth."""
    q, k_pages, v_pages, table, lengths = make_case(lengths=(32, 32, 32))
    out1 = paged_attention(q, k_pages, v_pages, table, lengths,
                           interpret=True)
    # Apply a pool permutation and rewrite the table through it.
    perm = np.random.default_rng(1).permutation(k_pages.shape[1])
    inv = np.argsort(perm)
    out2 = paged_attention(
        q, k_pages[:, inv], v_pages[:, inv],
        jnp.asarray(perm)[table], lengths, interpret=True)
    assert jnp.allclose(out1, out2, atol=1e-5)


def test_bfloat16_path():
    case = make_case(dtype=jnp.bfloat16, lengths=(7, 30, 21))
    out = paged_attention(*case, interpret=True)
    ref = paged_attention_reference(*case)
    assert jnp.allclose(out.astype(jnp.float32),
                        ref.astype(jnp.float32), atol=3e-2)


def test_rejects_bad_shapes():
    q, k_pages, v_pages, table, lengths = make_case()
    with pytest.raises(AssertionError):
        paged_attention(q[:, :3], k_pages, v_pages, table, lengths,
                        interpret=True)
