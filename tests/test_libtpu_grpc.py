"""End-to-end libtpu metrics client test against an in-process gRPC
server speaking the same wire protocol (SURVEY §4.3: fake device-info
source)."""

import asyncio

import pytest

grpc = pytest.importorskip("grpc")

from tests.test_protowire import build_metric_response  # noqa: E402
from tpumon.collectors.libtpu_grpc import (  # noqa: E402
    GRPC_METHOD,
    METRIC_DUTY_CYCLE,
    METRIC_HBM_TOTAL,
    METRIC_HBM_USAGE,
    LibtpuMetricsClient,
    encode_metric_request,
)
from tpumon import protowire as pw  # noqa: E402

CANNED = {
    METRIC_HBM_USAGE: {0: 8 * 2**30, 1: 4 * 2**30},
    METRIC_HBM_TOTAL: {0: 16 * 2**30, 1: 16 * 2**30},
    METRIC_DUTY_CYCLE: {0: 72.5, 1: 31.0},
}


async def _serve():
    server = grpc.aio.server()

    async def get_runtime_metric(request: bytes, context) -> bytes:
        name = pw.decode_message(request).first(1)
        values = CANNED.get(name)
        if values is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, f"unknown metric {name}")
        as_int = name != METRIC_DUTY_CYCLE
        return build_metric_response(values, as_int=as_int)

    service, method = GRPC_METHOD.strip("/").rsplit("/", 1)
    handler = grpc.unary_unary_rpc_method_handler(
        get_runtime_metric,
        request_deserializer=lambda b: b,
        response_serializer=lambda b: b,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service, {method: handler}),)
    )
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    return server, port


def test_snapshot_against_fake_metric_service():
    async def scenario():
        server, port = await _serve()
        client = LibtpuMetricsClient(addr=f"127.0.0.1:{port}")
        snap = await client.snapshot()
        await client.close()
        await server.stop(0)
        return snap

    snap = asyncio.run(scenario())
    assert snap is not None
    assert snap["hbm_used"] == {0: float(8 * 2**30), 1: float(4 * 2**30)}
    assert snap["hbm_total"][0] == float(16 * 2**30)
    assert snap["duty_pct"] == {0: 72.5, 1: 31.0}


def test_snapshot_none_when_service_absent():
    async def scenario():
        client = LibtpuMetricsClient(addr="127.0.0.1:1", timeout_s=0.5)
        snap = await client.snapshot()
        await client.close()
        return snap

    assert asyncio.run(scenario()) is None


def test_request_roundtrip_through_server():
    """The request our client sends must decode on a proto-faithful server."""
    req = encode_metric_request(METRIC_HBM_USAGE)
    assert pw.decode_message(req).first(1) == METRIC_HBM_USAGE
