"""Orbax checkpoint/resume of loadgen model params (SURVEY §5.4).

Covers the TPU-native resume path: params saved from one process layout
restore directly onto a dp×tp jax.sharding.Mesh (no gather-to-host), the
latest-step pointer survives partial writes, and a config mismatch
refuses to resume rather than loading an incompatible tree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding

from tpumon.loadgen.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
    saved_model_config,
)
from tpumon.loadgen.model import (
    ModelConfig,
    init_params,
    param_shardings,
)

CFG = ModelConfig(
    vocab=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2, d_ff=64, max_seq=16
)


@pytest.fixture
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def trees_equal(a, b) -> bool:
    return all(
        jax.tree.leaves(jax.tree.map(lambda x, y: bool(jnp.allclose(x, y)), a, b))
    )


def test_save_restore_round_trip(tmp_path, params):
    d = str(tmp_path)
    save_checkpoint(d, params, step=3, cfg=CFG)
    assert latest_step(d) == 3
    assert saved_model_config(d) == CFG
    restored, step = restore_checkpoint(d, like=params, cfg=CFG)
    assert step == 3
    assert trees_equal(params, restored)


def test_restore_latest_of_many_steps(tmp_path, params):
    d = str(tmp_path)
    save_checkpoint(d, params, step=1, cfg=CFG)
    bumped = jax.tree.map(lambda x: x + 1, params)
    save_checkpoint(d, bumped, step=2, cfg=CFG)
    restored, step = restore_checkpoint(d, like=params)
    assert step == 2
    assert trees_equal(bumped, restored)


def test_restore_onto_sharded_mesh(tmp_path, params):
    """Params saved unsharded restore straight onto a dp×tp mesh with the
    training shardings — each leaf lands distributed, not single-device."""
    d = str(tmp_path)
    save_checkpoint(d, params, step=0, cfg=CFG)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "model"))
    shardings = param_shardings(mesh, params)
    like = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        params,
        shardings,
    )
    restored, _ = restore_checkpoint(d, like=like, cfg=CFG)
    leaves, s_leaves = jax.tree.leaves(restored), jax.tree.leaves(shardings)
    assert all(
        leaf.sharding == s for leaf, s in zip(leaves, s_leaves)
    )
    assert trees_equal(params, restored)


def test_nothing_to_resume(tmp_path, params):
    assert latest_step(str(tmp_path)) is None
    assert restore_checkpoint(str(tmp_path), like=params) is None


def test_config_mismatch_refuses_resume(tmp_path, params):
    d = str(tmp_path)
    save_checkpoint(d, params, step=0, cfg=CFG)
    other = dataclasses.replace(CFG, n_layers=2)
    assert restore_checkpoint(d, like=params, cfg=other) is None


def test_meta_pointing_at_missing_step_dir(tmp_path, params):
    import shutil

    d = str(tmp_path)
    path = save_checkpoint(d, params, step=5, cfg=CFG)
    shutil.rmtree(path)  # simulate a partially-deleted checkpoint
    assert latest_step(d) is None
    assert restore_checkpoint(d, like=params) is None


def test_schedule_only_change_still_resumes(tmp_path):
    """remat/attention/attn_block_k change memory scheduling, not the
    params - a resumed run with a different schedule must restore
    (VERDICT-class bug: it used to silently cold-start at step 0)."""
    import dataclasses

    from tpumon.loadgen.checkpoint import restore_checkpoint, save_checkpoint
    from tpumon.loadgen.model import ModelConfig, init_params

    cfg = ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2,
                      n_kv_heads=2, d_ff=64, max_seq=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path), params, step=7, cfg=cfg)
    resched = dataclasses.replace(cfg, remat=True, attention="chunked",
                                  attn_block_k=16)
    out = restore_checkpoint(str(tmp_path), like=params, cfg=resched)
    assert out is not None and out[1] == 7
    # A REAL architecture change still refuses.
    other = dataclasses.replace(cfg, d_ff=128)
    assert restore_checkpoint(str(tmp_path), like=params, cfg=other) is None
