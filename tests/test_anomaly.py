"""EWMA anomaly detection (ISSUE 4): detector fire/clear with
hysteresis on synthetic drift — one fired/cleared pair per incident,
no flapping — plus the journal events, the minor ``anomaly.<series>``
alert through the engine, and the sampler integration (anomaly/events
stage spans, config switch, exporter gauge)."""

import asyncio
import random

from tests.test_server_api import serve
from tpumon.anomaly import AnomalyBank, AnomalyConfig, EwmaDetector
from tpumon.events import EventJournal

# ------------------------------------------------------------- detector


def drive(det, values, start=0):
    out = []
    for i, v in enumerate(values):
        tr = det.update(v, start + i)
        if tr:
            out.append(tr)
    return out


class TestEwmaDetector:
    def test_no_verdict_during_warmup(self):
        det = EwmaDetector("hbm")
        # A wild swing inside the warmup window must not fire.
        assert drive(det, [50.0] * 10 + [500.0] * 10) == []

    def test_hbm_ramp_fires_then_clears_without_flapping(self):
        """The acceptance scenario: baseline, ramp, plateau. Exactly one
        fired, exactly one cleared, nothing else — the plateau becomes
        the new normal."""
        det = EwmaDetector("hbm")
        trs = drive(det, [50.0] * 40)  # settle baseline
        ramp = [50.0 + 4.0 * k for k in range(1, 11)]  # 54 → 90
        trs += drive(det, ramp, start=40)
        trs += drive(det, [90.0] * 300, start=50)
        assert trs == ["fired", "cleared"]
        assert det.state == "normal"
        assert abs(det.mean - 90.0) < 1.0  # converged to the new level

    def test_refires_on_second_excursion(self):
        det = EwmaDetector("hbm")
        trs = drive(det, [50.0] * 40)
        trs += drive(det, [90.0] * 200, start=40)
        trs += drive(det, [140.0] * 200, start=240)
        assert trs == ["fired", "cleared", "fired", "cleared"]

    def test_single_spike_rejected_by_fire_hold(self):
        det = EwmaDetector("tick_ms")
        values = [5.0] * 60
        values[45] = 500.0  # one GC pause
        assert drive(det, values) == []
        assert det.state == "normal"

    def test_noisy_shift_fires_once(self):
        rnd = random.Random(3)
        det = EwmaDetector("duty")
        drive(det, [50.0 + rnd.uniform(-1, 1) for _ in range(40)])
        trs = drive(det, [58.0 + rnd.uniform(-4, 4) for _ in range(260)], 40)
        assert trs.count("fired") == 1
        assert trs.count("cleared") == 1

    def test_min_sigma_floor_guards_flat_series(self):
        # A near-constant series with numeric dust must not fire.
        det = EwmaDetector("duty")
        assert drive(det, [70.0 + 1e-9 * (i % 3) for i in range(200)]) == []

    def test_to_json_shape(self):
        det = EwmaDetector("hbm")
        drive(det, [50.0] * 5)
        j = det.to_json()
        assert {"state", "n", "mean", "sigma", "z"} <= set(j)


# ----------------------------------------------------------------- bank


class TestAnomalyBank:
    def test_journal_events_on_fire_and_clear(self):
        journal = EventJournal()
        bank = AnomalyBank(journal)
        for i in range(40):
            bank.observe({"hbm": 50.0}, ts=float(i))
        for i in range(40, 340):
            bank.observe({"hbm": 90.0}, ts=float(i))
        evs = [e for e in journal.events() if e["kind"] == "anomaly"]
        assert [e["severity"] for e in evs] == ["minor", "info"]
        assert evs[0]["series"] == "hbm"
        assert "drifting" in evs[0]["msg"]
        assert {"z", "value", "mean"} <= set(evs[0])

    def test_active_lists_fired_series_while_anomalous(self):
        bank = AnomalyBank()
        for i in range(40):
            bank.observe({"hbm": 50.0, "duty": 60.0}, ts=float(i))
        for i in range(40, 46):
            bank.observe({"hbm": 95.0, "duty": 60.0}, ts=float(i))
        active = bank.active()
        assert [a["series"] for a in active] == ["hbm"]
        assert active[0]["z"] != 0
        assert bank.to_json()["hbm"]["state"] == "anomalous"

    def test_none_values_skipped(self):
        bank = AnomalyBank()
        bank.observe({"hbm": None, "duty": 50.0})
        assert set(bank.detectors) == {"duty"}


# --------------------------------------------------------- engine rule


class TestAnomalyAlertRule:
    def test_minor_alert_fires_and_resolves_with_detector(self):
        from tpumon.alerts import AlertEngine

        e = AlertEngine()
        anomaly = {"series": "hbm", "z": 5.2, "value": 91.0, "mean": 50.0}
        out = e.evaluate(anomalies=[anomaly], now=1000.0)
        assert [a["key"] for a in out["minor"]] == ["anomaly.hbm"]
        assert "z=5.2" in out["minor"][0]["desc"]
        out = e.evaluate(anomalies=None, now=1001.0)
        assert out["minor"] == []
        states = [ev["state"] for ev in e.events]
        assert states == ["fired", "resolved"]


# ------------------------------------------------------- sampler wiring


class TestSamplerIntegration:
    def test_anomaly_and_events_stages_traced(self):
        sampler, server = serve()
        loop = asyncio.new_event_loop()
        try:
            for _ in range(3):
                loop.run_until_complete(sampler.tick_fast())
            stages = set(sampler.tracer.stage_hist)
            assert {"anomaly", "events"} <= stages
            # The detectors saw this tick's fleet series.
            assert {"duty", "hbm"} <= set(sampler.anomaly.detectors)
        finally:
            loop.close()

    def test_anomaly_detect_off_disables_cleanly(self):
        sampler, server = serve({"TPUMON_ANOMALY_DETECT": "0"})
        loop = asyncio.new_event_loop()
        try:
            for _ in range(3):
                loop.run_until_complete(sampler.tick_fast())
            assert sampler.anomaly is None
            assert "anomaly" not in sampler.tracer.stage_hist
            # /api/health omits the anomaly block entirely.
            assert "anomaly" not in sampler.health_json()
        finally:
            loop.close()

    def test_exporter_gauge_per_series(self):
        import json

        sampler, server = serve()
        loop = asyncio.new_event_loop()
        try:
            for _ in range(3):
                loop.run_until_complete(sampler.tick_fast())
            # A detector forced anomalous shows as 1 in /metrics.
            det = sampler.anomaly.detectors["hbm"]
            det.state = "anomalous"
            sampler.journal.record("anomaly", "minor", "hbm", "forced")
            loop.run_until_complete(sampler.tick_fast())
            _, _, body, _ = loop.run_until_complete(
                server.handle_ex("GET", "/metrics")
            )
            text = body.decode()
            assert 'tpumon_anomaly_active{series="hbm"} 1' in text
            assert 'tpumon_anomaly_active{series="duty"} 0' in text
            _, _, body, _ = loop.run_until_complete(
                server.handle_ex("GET", "/api/health")
            )
            assert json.loads(body)["anomaly"]["hbm"]["state"] == "anomalous"
        finally:
            loop.close()

    def test_config_keys(self):
        from tpumon.config import load_config

        cfg = load_config(
            env={
                "TPUMON_ANOMALY_Z_FIRE": "6",
                "TPUMON_ANOMALY_WARMUP": "10",
                "TPUMON_EVENTS_RING": "128",
                "TPUMON_EVENTS_PATH": "/tmp/ev.jsonl",
                "TPUMON_EVENTS_INTERVAL_S": "5",
            }
        )
        assert cfg.anomaly_z_fire == 6.0
        assert cfg.anomaly_warmup == 10
        assert cfg.events_ring == 128
        assert cfg.events_path == "/tmp/ev.jsonl"
        assert cfg.events_interval_s == 5.0
