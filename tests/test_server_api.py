"""Integration tests: the real server wired to fake backends, asserting
the API contracts of SURVEY §2.3 (re-keyed for TPU)."""

import asyncio
import dataclasses
import json
import urllib.request

import pytest

from tests.fakes import fake_jetstream, fake_k8s_api
from tests.test_k8s import pod_doc
from tests.test_serving import JETSTREAM_TEXT
from tpumon.app import build
from tpumon.config import load_config


def serve(env=None):
    """Build the app from env config; returns (cfg, sampler, server)."""
    base = {
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_K8S_MODE": "none",
    }
    base.update(env or {})
    cfg = load_config(env=base)
    return build(cfg)


async def run_app(sampler, server):
    await sampler.tick_all()
    await server.start()
    return server.port


def get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def get_status(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


class TestApiContracts:
    @pytest.fixture()
    def app(self):
        sampler, server = serve()
        loop = asyncio.new_event_loop()
        port = loop.run_until_complete(run_app(sampler, server))
        yield loop, port, sampler
        loop.run_until_complete(server.stop())
        loop.close()

    def _get(self, app, path):
        loop, port, _ = app
        return loop.run_until_complete(asyncio.to_thread(get_json, port, path))

    def test_host_metrics_contract(self, app):
        d = self._get(app, "/api/host/metrics")
        # Reference shape (monitor_server.js:75-79) + health envelope.
        assert {"load_1min", "percent", "cores"} <= set(d["cpu"])
        assert {"total", "used", "percent"} <= set(d["memory"])
        assert {"total", "used", "percent"} <= set(d["disk"])
        assert d["health"]["ok"] is True

    def test_accel_metrics_contract(self, app):
        d = self._get(app, "/api/accel/metrics")
        assert len(d["chips"]) == 8
        chip = d["chips"][0]
        assert {
            "chip", "host", "slice", "kind", "mxu_duty_pct",
            "hbm_used", "hbm_total", "hbm_pct", "temp_c",
        } <= set(chip)
        assert d["slices"][0]["reporting_chips"] == 8

    def test_gpu_compat_contract(self, app):
        d = self._get(app, "/api/gpu/metrics")
        # Reference shape: [{name, utilization, memoryUsed, memoryTotal,
        # temperature}] (monitor_server.js:90).
        assert len(d) == 8
        assert {"name", "utilization", "memoryUsed", "memoryTotal", "temperature"} <= set(d[0])
        assert d[0]["memoryTotal"] == 16 * 1024  # MB

    def test_alerts_contract(self, app):
        d = self._get(app, "/api/alerts")
        for sev in ("minor", "serious", "critical"):
            assert isinstance(d[sev], list)
            for a in d[sev]:
                assert {"title", "desc", "fix"} <= set(a)

    def test_history_contract(self, app):
        d = self._get(app, "/api/history")
        assert d["source"] == "ring"
        for key in ("cpu", "memory", "disk", "mxu", "hbm", "temp"):
            assert "labels" in d[key] and "data" in d[key]
            assert len(d[key]["labels"]) == len(d[key]["data"])

    def test_history_window_param(self, app):
        loop, port, _ = app
        d = self._get(app, "/api/history?window=3h")
        assert d["window_s"] == 3 * 3600
        assert d["step_s"] >= 30
        # Oversized windows clamp to the long tier; junk is a 400.
        d = self._get(app, "/api/history?window=99d")
        assert d["window_s"] == 24 * 3600
        assert (
            loop.run_until_complete(
                asyncio.to_thread(get_status, port, "/api/history?window=bogus")
            )
            == 400
        )

    def test_metrics_exporter(self, app):
        loop, port, _ = app

        def fetch():
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                return r.read().decode()

        text = loop.run_until_complete(asyncio.to_thread(fetch))
        assert "tpu_mxu_duty_cycle_pct{" in text
        assert "tpu_hbm_used_bytes{" in text
        assert 'slice="slice-0"' in text
        assert "tpumon_samples_total{" in text

    def test_dashboard_and_errors(self, app):
        loop, port, _ = app

        def statuses():
            return (
                get_status(port, "/"),
                get_status(port, "/nope"),
                get_status(port, "/api/history?x=1"),
            )

        ok, nf, qs = loop.run_until_complete(asyncio.to_thread(statuses))
        assert (ok, nf, qs) == (200, 404, 200)


def test_full_stack_with_fake_backends():
    """All fake upstreams live at once: K8s apiserver + JetStream — the
    §4.3 integration scenario. History comes from the in-process TSDB
    (the external-Prometheus path is retired, ISSUE 12); the legacy
    prometheus_url knob must deprecate loudly, not change behavior."""
    k8s = fake_k8s_api([pod_doc(name="js", phase="Running"), pod_doc(name="bad", phase="Failed")])
    js = fake_jetstream(JETSTREAM_TEXT)
    try:
        sampler, server = serve(
            {
                "TPUMON_PROMETHEUS_URL": "http://127.0.0.1:1",  # deprecated
                "TPUMON_K8S_MODE": "api",
                "TPUMON_K8S_API_URL": k8s.url,
                "TPUMON_SERVING_TARGETS": js.url,
            }
        )
        assert server.history.prometheus_deprecated is True

        async def scenario():
            await sampler.tick_all()
            await server.start()
            port = server.port
            pods = await asyncio.to_thread(get_json, port, "/api/k8s/pods")
            assert [p["name"] for p in pods["pods"]] == ["js", "bad"]
            assert pods["health"]["ok"] is True

            alerts = await asyncio.to_thread(get_json, port, "/api/alerts")
            keys = {a["key"] for sev in ("minor", "serious", "critical") for a in alerts[sev]}
            assert "pod.default/bad.failed" in keys

            hist = await asyncio.to_thread(get_json, port, "/api/history")
            assert hist["source"] == "ring"
            assert hist["cpu"]["data"], "host cpu series missing from ring"
            # The same store answers rich expressions via the query
            # engine route (tpumon.query).
            q = await asyncio.to_thread(
                get_json, port, "/api/query?query=avg_over_time(cpu[5m])"
            )
            assert q["result"] and q["result"][0]["value"] is not None

            serving = await asyncio.to_thread(get_json, port, "/api/serving")
            t = serving["targets"][0]
            assert t["ok"] and t["tokens_total"] == 80000

            health = await asyncio.to_thread(get_json, port, "/api/health")
            assert set(health["sources"]) == {"host", "accel", "k8s", "serving"}
            assert all(s["ok"] for s in health["sources"].values())
            await server.stop()

        asyncio.run(scenario())
    finally:
        k8s.close()
        js.close()


def test_degraded_sources_render_not_error():
    """SURVEY §7: every config must render without errors when upstream
    sources are absent — with explicit source health."""
    sampler, server = serve(
        {
            "TPUMON_PROMETHEUS_URL": "http://127.0.0.1:1",
            "TPUMON_K8S_MODE": "api",
            "TPUMON_K8S_API_URL": "http://127.0.0.1:1",
            "TPUMON_SERVING_TARGETS": "http://127.0.0.1:1",
        }
    )

    async def scenario():
        await sampler.tick_all()
        await server.start()
        port = server.port
        for path in ("/api/host/metrics", "/api/accel/metrics", "/api/k8s/pods",
                     "/api/history", "/api/alerts", "/api/serving", "/api/health"):
            d = await asyncio.to_thread(get_json, port, path)
            assert d is not None
        pods = await asyncio.to_thread(get_json, port, "/api/k8s/pods")
        assert pods["pods"] == [] and pods["health"]["ok"] is False
        hist = await asyncio.to_thread(get_json, port, "/api/history")
        assert hist["source"] == "ring"  # prometheus down -> ring fallback
        await server.stop()

    asyncio.run(scenario())
