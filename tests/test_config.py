import json

import pytest

from tpumon.config import Config, Thresholds, TriLevel, load_config, parse_duration


def test_defaults_match_reference_constants():
    cfg = Config()
    # Reference parity: port 8888 (monitor_server.js:10), 30m/30s history
    # (monitor_server.js:38), 70/85/95 thresholds (monitor_server.js:163-184).
    assert cfg.port == 8888
    assert cfg.history_window_s == 1800
    assert cfg.history_step_s == 30
    assert cfg.thresholds.cpu_pct == TriLevel(70, 85, 95)
    assert cfg.thresholds.temp_c == TriLevel(None, 75, 85)


def test_parse_duration():
    assert parse_duration("30m") == 1800
    assert parse_duration("45s") == 45
    assert parse_duration("2h") == 7200
    assert parse_duration("1d") == 86400
    assert parse_duration(90) == 90
    assert parse_duration("bogus", default=1800) == 1800
    with pytest.raises(ValueError):
        parse_duration("bogus")


def test_trilevel_severity_boundaries():
    t = TriLevel(70, 85, 95)
    # Strict > comparisons like the reference (monitor_server.js:163-175).
    assert t.severity(70) is None
    assert t.severity(70.1) == "minor"
    assert t.severity(85) == "minor"
    assert t.severity(85.1) == "serious"
    assert t.severity(95) == "serious"
    assert t.severity(95.1) == "critical"
    t2 = TriLevel(None, 75, 85)
    assert t2.severity(74) is None
    assert t2.severity(76) == "serious"
    assert t2.severity(86) == "critical"


def test_load_config_file_env_overrides(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(
        json.dumps(
            {
                "port": 9000,
                "history_window": "1h",
                "collectors": ["host", "accel"],
                "thresholds": {"cpu_pct": [60, 80, 90], "temp_c": [70, 80]},
                "expected_slice_chips": {"slice-0": 8},
            }
        )
    )
    cfg = load_config(
        path=str(p),
        env={"TPUMON_ACCEL_BACKEND": "fake:v5e-8", "TPUMON_PORT": "9100"},
    )
    assert cfg.port == 9100  # env beats file
    assert cfg.history_window_s == 3600
    assert cfg.collectors == ("host", "accel")
    assert cfg.accel_backend == "fake:v5e-8"
    assert cfg.thresholds.cpu_pct == TriLevel(60, 80, 90)
    assert cfg.thresholds.temp_c == TriLevel(None, 70, 80)
    assert cfg.expected_slice_chips == {"slice-0": 8}


def test_load_config_env_lists_and_unknown_key():
    cfg = load_config(env={"TPUMON_SERVING_TARGETS": "http://a:9000, http://b:9000"})
    assert cfg.serving_targets == ("http://a:9000", "http://b:9000")
    with pytest.raises(ValueError):
        load_config(env={"TPUMON_NO_SUCH_KEY": "1"})


def test_effective_cpu_count_autodetect():
    assert Config(cpu_count=4).effective_cpu_count() == 4
    assert Config().effective_cpu_count() >= 1


def test_scalar_for_trilevel_threshold_rejected():
    """A bare number for a TriLevel threshold must fail at load time, not
    crash the alert engine later (code-review finding)."""
    with pytest.raises(ValueError):
        load_config(env={"TPUMON_THRESHOLDS": json.dumps({"cpu_pct": 90})})
    with pytest.raises(ValueError):
        load_config(env={"TPUMON_THRESHOLDS": json.dumps({"mxu_idle_pct": [1, 2, 3]})})
    # scalar for scalar field is fine
    cfg = load_config(env={"TPUMON_THRESHOLDS": json.dumps({"mxu_idle_pct": 2.5})})
    assert cfg.thresholds.mxu_idle_pct == 2.5


def test_long_window_duration_keys_configurable():
    # Regression: the coarse-tier durations must be reachable from env
    # (and thus config files), "48h"-style strings included.
    from tpumon.config import load_config

    cfg = load_config(
        env={
            "TPUMON_HISTORY_LONG_WINDOW": "48h",
            "TPUMON_HISTORY_COARSE_STEP": "2m",
        }
    )
    assert cfg.history_long_window_s == 48 * 3600
    assert cfg.history_coarse_step_s == 120
