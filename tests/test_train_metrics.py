"""Training-metrics ingest: trainer /metrics -> collector -> panel data.

The trainer (tpumon.loadgen.train) publishes tpumon_train_* families;
the serving collector distills them into the training panel's fields
(step, loss, step time, token rate, goodput, checkpoint step).
"""

from __future__ import annotations

import asyncio
import urllib.request

from tpumon.collectors.serving import (
    ServingCollector,
    distill_serving_metrics,
)
from tpumon.loadgen.train import TrainMetrics, start_metrics_server


def test_train_metrics_text_shape():
    m = TrainMetrics()
    m.observe_step(0, 0.5, 512)
    m.observe_step(1, 0.3, 512)
    m.loss = 3.25
    m.ckpt_step = 1
    text = m.metrics_text()
    assert "tpumon_train_step 1" in text
    assert "tpumon_train_tokens_total 1024" in text
    assert "tpumon_train_loss 3.25" in text
    assert "tpumon_train_checkpoint_step 1" in text
    assert "tpumon_train_goodput_pct" in text
    # EMA moved from 0.5 toward 0.3.
    assert "tpumon_train_step_time_seconds 0.48" in text


def test_mfu_computed_and_distilled():
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import detect_peak_flops, flops_per_token

    cfg = ModelConfig(vocab=256, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq=64)
    fpt = flops_per_token(cfg, seq=32)
    # 6N dominates and both terms are positive.
    assert fpt > 6 * (2 * 256 * 64)
    # Peak 1 TFLOP/s, one step of 1024 tokens in 1s -> MFU% directly.
    m = TrainMetrics(flops_per_token=fpt, peak_flops=1e12)
    m.observe_step(0, 1.0, 1024)
    expect = 100.0 * 1024 * fpt / 1e12
    assert abs(m.mfu_pct - expect) < 1e-6
    d = distill_serving_metrics(m.metrics_text(), now=1000.0)
    assert abs(d["train_mfu_pct"] - round(expect, 2)) < 0.01

    # Unknown hardware: no peak -> no MFU gauge at all.
    m2 = TrainMetrics(flops_per_token=fpt, peak_flops=None)
    m2.observe_step(0, 1.0, 1024)
    assert "mfu" not in m2.metrics_text()
    # CPU test mesh has no TPU kind -> detection declines to guess.
    assert detect_peak_flops() is None


def test_distill_train_fields_and_token_rate():
    m = TrainMetrics()
    m.observe_step(9, 0.4, 4096)
    m.loss = 2.5
    first = distill_serving_metrics(m.metrics_text(), now=1000.0)
    assert first["train_step"] == 9
    assert first["train_loss"] == 2.5
    assert first["train_step_time_ms"] == 400.0
    m.observe_step(10, 0.4, 4096)
    second = distill_serving_metrics(m.metrics_text(), prev=first, now=1002.0)
    assert second["train_tokens_per_sec"] == 4096 / 2.0


def test_trainer_http_scrape_end_to_end():
    m = TrainMetrics()
    m.observe_step(3, 0.2, 256)
    httpd, url = start_metrics_server(m, port=0)
    try:
        with urllib.request.urlopen(url) as r:
            assert b"tpumon_train_step 3" in r.read()
        collector = ServingCollector(targets=(url,))
        sample = asyncio.run(collector.collect())
        assert sample.ok
        assert sample.data[0]["train_step"] == 3
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_fake_trainer_target():
    collector = ServingCollector(targets=("fake:trainer",))
    sample = asyncio.run(collector.collect())
    d = sample.data[0]
    assert d["ok"] and d["train_step"] >= 0
    assert d["train_loss"] > 0 and d["train_goodput_pct"] > 0


def test_run_train_feeds_metrics():
    import jax

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig, run_train

    m = TrainMetrics()
    cfg = TrainConfig(
        model=ModelConfig(
            vocab=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
            d_ff=64, max_seq=16,
        ),
        steps=3, batch=2, seq=8,
    )
    out = run_train(cfg, mesh=None, metrics=m)
    assert m.step == 2
    assert m.tokens_total == 3 * 2 * 8
    assert m.loss is not None and abs(m.loss - out["loss"]) < 1e-6
    assert m.step_time_ema_s is not None and m.step_time_ema_s > 0


def test_sentinel_gauges_omitted_before_first_step():
    m = TrainMetrics()
    text = m.metrics_text()
    assert "tpumon_train_step " not in text
    assert "tpumon_train_checkpoint_step" not in text
    m.observe_step(0, 0.1, 64)
    text = m.metrics_text()
    assert "tpumon_train_step 0" in text
    assert "tpumon_train_checkpoint_step" not in text  # no --ckpt-dir


def test_exporter_reexports_train_series():
    # The tpumon_train_* PROM_QUERIES re-keys must resolve against our own
    # /metrics even when Prometheus doesn't scrape each trainer directly.
    import asyncio as _asyncio

    from tpumon.app import build
    from tpumon.config import load_config
    from tpumon.exporter import render_exporter

    cfg = load_config(
        env={
            "TPUMON_ACCEL_BACKEND": "none",
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host,serving",
            "TPUMON_SERVING_TARGETS": "fake:trainer",
            "TPUMON_PORT": "0",
        }
    )
    sampler, _ = build(cfg)
    _asyncio.run(sampler.tick_serving())
    text = render_exporter(sampler)
    assert 'tpumon_monitor_train_step{target="fake:trainer"}' in text
    assert 'tpumon_monitor_train_loss{target="fake:trainer"}' in text
    assert "tpumon_monitor_train_tokens_total" in text


# ---------------- training-stall alert rule ----------------------------


def _train_target(step, ok=True):
    return [{"target": "t:9177", "ok": ok, "train_step": step}]


def test_train_stall_alert_fires_and_clears():
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds

    e = AlertEngine(Thresholds(train_stall_s=60))
    e.evaluate(serving=_train_target(10), now=1000.0)
    # Advancing step: healthy.
    e.evaluate(serving=_train_target(11), now=1030.0)
    assert e.last["serious"] == []
    # Stuck for under the threshold: not yet.
    out = e.evaluate(serving=_train_target(11), now=1080.0)
    assert out["serious"] == []
    # Stuck past the threshold: fires with the stuck duration.
    out = e.evaluate(serving=_train_target(11), now=1095.0)
    assert [a["key"] for a in out["serious"]] == ["train.t:9177.stalled"]
    # Progress resumes: resolves.
    out = e.evaluate(serving=_train_target(12), now=1100.0)
    assert out["serious"] == []


def test_train_stall_ignores_unreachable_and_disabled():
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds

    e = AlertEngine(Thresholds(train_stall_s=60))
    # Unreachable target: the scrape-failure rule owns it, not the stall
    # rule (step field may be stale garbage).
    e.evaluate(serving=_train_target(5, ok=False), now=1000.0)
    e.evaluate(serving=_train_target(5, ok=False), now=2000.0)
    assert all(a["key"] != "train.t:9177.stalled" for a in e.last["serious"])
    # Disabled via threshold 0.
    e2 = AlertEngine(Thresholds(train_stall_s=0))
    e2.evaluate(serving=_train_target(5), now=1000.0)
    out = e2.evaluate(serving=_train_target(5), now=9000.0)
    assert out["serious"] == []


def test_train_stall_clock_resets_after_outage():
    # Regression: a trainer that recovers from an outage at the same step
    # (checkpoint restart) must get a fresh observation window, not an
    # instant stall page computed against the pre-outage timestamp.
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds

    e = AlertEngine(Thresholds(train_stall_s=60))
    e.evaluate(serving=_train_target(10), now=1000.0)
    for t in (1100.0, 1500.0):  # 400s unreachable
        e.evaluate(serving=_train_target(10, ok=False), now=t)
    out = e.evaluate(serving=_train_target(10), now=1600.0)  # recovered
    assert all(a["key"] != "train.t:9177.stalled" for a in out["serious"])
    # But genuinely stuck after recovery still fires.
    out = e.evaluate(serving=_train_target(10), now=1700.0)
    assert any(a["key"] == "train.t:9177.stalled" for a in out["serious"])
    # Vanished targets are pruned from the progress map.
    e.evaluate(serving=[], now=1800.0)
    assert e._train_progress == {}
