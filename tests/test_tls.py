"""Server-side TLS (the PR 7 follow-up): --tls-cert/--tls-key wrap the
listener in an ssl.SSLContext, so the SLO/alerting surface isn't
plaintext. Exercised against the checked-in self-signed fixture cert
(tests/fixtures/tls/, CN=tpumon-test, SAN IP:127.0.0.1 — valid ~100
years so the suite never starts failing on a calendar date)."""

import asyncio
import json
import os
import ssl
import urllib.request

import pytest

from tpumon.app import build
from tpumon.config import load_config

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "tls")
CERT = os.path.join(FIXTURES, "cert.pem")
KEY = os.path.join(FIXTURES, "key.pem")


def mk_cfg(**extra):
    return load_config(env={
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_K8S_MODE": "none",
        "TPUMON_COLLECTORS": "host,accel",
        **extra,
    })


def test_https_terminates_on_the_listener():
    cfg = mk_cfg(TPUMON_TLS_CERT=CERT, TPUMON_TLS_KEY=KEY)
    sampler, server = build(cfg)

    async def scenario():
        await sampler.tick_all()
        await server.start()
        port = server.port
        client = ssl.create_default_context(cafile=CERT)

        def get(path):
            with urllib.request.urlopen(
                f"https://127.0.0.1:{port}{path}", timeout=10,
                context=client,
            ) as r:
                return r.status, json.load(r)

        status, health = await asyncio.to_thread(get, "/api/health")
        assert status == 200
        assert health["sources"]["accel"]["ok"]
        status, slo = await asyncio.to_thread(get, "/api/slo")
        assert status == 200 and slo == {"slos": [], "evaluated_at": None}

        # A client that does not trust the self-signed cert is refused
        # during the handshake — the listener really is TLS, not
        # plaintext with a cert lying around.
        def get_untrusted():
            urllib.request.urlopen(
                f"https://127.0.0.1:{port}/api/health", timeout=10,
                context=ssl.create_default_context(),
            )

        with pytest.raises(Exception) as exc:
            await asyncio.to_thread(get_untrusted)
        assert "certificate" in str(exc.value).lower() or isinstance(
            exc.value, ssl.SSLError)
        await server.stop()

    asyncio.run(scenario())


def test_combined_pem_key_defaults_to_cert(tmp_path):
    combined = tmp_path / "combined.pem"
    with open(KEY) as kf, open(CERT) as cf:
        combined.write_text(kf.read() + cf.read())
    cfg = mk_cfg(TPUMON_TLS_CERT=str(combined))
    sampler, server = build(cfg)

    async def scenario():
        await sampler.tick_all()
        await server.start()
        client = ssl.create_default_context(cafile=CERT)

        def get():
            with urllib.request.urlopen(
                f"https://127.0.0.1:{server.port}/api/health",
                timeout=10, context=client,
            ) as r:
                return r.status

        assert await asyncio.to_thread(get) == 200
        await server.stop()

    asyncio.run(scenario())


def test_key_without_cert_refuses_to_start():
    cfg = mk_cfg(TPUMON_TLS_KEY=KEY)
    sampler, server = build(cfg)

    async def scenario():
        with pytest.raises(ValueError, match="tls_key is set but"):
            await server.start()

    asyncio.run(scenario())


def test_plain_http_client_is_not_served_by_a_tls_listener():
    cfg = mk_cfg(TPUMON_TLS_CERT=CERT, TPUMON_TLS_KEY=KEY)
    sampler, server = build(cfg)

    async def scenario():
        await sampler.tick_all()
        await server.start()

        def get_plain():
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/health", timeout=5)

        with pytest.raises(Exception):
            await asyncio.to_thread(get_plain)
        await server.stop()

    asyncio.run(scenario())
