"""Root-HA soak (ISSUE 16 acceptance): the federation tree survives the
death of its own root. A real leaf → aggregator → active+standby root
pair, the aggregator dual-homed (``federate_up`` primary,standby), both
roots scraping the PR 13 traffic-sim serving engine so the SLO burn
page and the shed remedy live on BOTH roots — then the active root is
killed mid-burn:

- while the active root leads, the standby's identical policy set is
  FENCED (``actuate``/``fenced`` journal events, zero engine actions:
  two roots can never both shed);
- the kill promotes the standby with a bumped generation (fencing
  token), the aggregator's uplink rotates and keyframe-resyncs, and the
  standby serves fleet view + firing SLO page + a real shed — within
  one keyframe cadence of the kill;
- the old root restarts and rejoins as STANDBY despite its bootstrap
  initial-leader flag (an observed leader always wins), fenced;
- wedging the new leader (lease never renewed again — the
  wedged-but-alive regression) self-fences it within one lease and the
  standby takes over with the next generation; the wedged root's
  actuation stays refused throughout.

Satellites pinned alongside: decorrelated-jitter reconnect spread over
64 simulated uplinks, the chaos ``partition`` verb blackholing a live
uplink (frames dropped, socket open, keyframe resync on heal), the
``--chaos`` grammar split, and SSE slow-consumer drop-and-resync.
"""

import asyncio
import json
import random
import time
import urllib.request

from tests.test_server_api import get_json, serve
from tpumon.app import build
from tpumon.collectors.chaos import Fault, split_link_faults
from tpumon.config import load_config
from tpumon.loadgen.serving import ServingEngine, start_metrics_server
from tpumon.loadgen.traffic import TenantSpec, TrafficSim
from tpumon.resilience import decorrelated_jitter

SAMPLE_INTERVAL_S = 0.25
SERVING_INTERVAL_S = 0.25
LEASE_S = 0.5
TTFT_THRESHOLD_MS = 700.0
DEGRADE_STALL_S = 1.0
# Failover budget: the uplink resync is bounded by one keyframe cadence
# (30 frames x the tick) and promotion by 2x the lease; measured
# end-to-end failover is ~1-2 s (bench.py federation_ha), so this holds
# an order of magnitude of full-suite-load headroom.
FAILOVER_BUDGET_S = 30 * SAMPLE_INTERVAL_S + 4.0

SLOS = [{
    "name": "chat_ttft",
    "tenant": "chat",
    "expr": f'serving.ttft_p95_ms{{tenant="chat"}} > {TTFT_THRESHOLD_MS:g}',
    "target": 0.99,
    "window": "1h",
    "fast": ["1s", "3s"],
    "slow": ["2s", "6s"],
}]

# One remedy, deliberately NOT curative: the scheduler stall dominates
# TTFT whatever the load, so the page keeps firing under the shed and
# the soak can kill the leader MID-BURN with the page + fired policy
# still live. clear_hold is parked high for the same reason — no revert
# races the failover assertions.
ACTUATIONS = [{
    "name": "shed_load", "when": 'slo.paging{slo="chat_ttft"} > 0',
    "action": "shed", "tenant": "*", "fraction": 0.5,
    "cooldown_s": 0, "fire_hold": 1, "clear_hold": 500,
}]


def _mk(**env):
    base = {
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_K8S_MODE": "none",
        "TPUMON_COLLECTORS": "accel",
        "TPUMON_SAMPLE_INTERVAL_S": str(SAMPLE_INTERVAL_S),
        "TPUMON_HISTORY_PER_CHIP": "0",
        "TPUMON_ANOMALY_DETECT": "0",
    }
    base.update(env)
    return build(load_config(env=base))


async def wait_until(fn, what: str, timeout_s: float = 30.0):
    """Poll a sync ``fn`` until truthy, always off the event-loop
    thread: the fns here do blocking HTTP against in-process servers
    sharing this loop, and a blocking call ON the loop would deadlock
    against the very server it polls."""
    t0 = time.monotonic()
    while True:
        v = await asyncio.to_thread(fn)
        if v:
            return v
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError(f"ha soak: timed out waiting for {what}")
        await asyncio.sleep(0.05)


def _root_env(node: str, metrics_port: int, **extra):
    env = {
        "TPUMON_ACCEL_BACKEND": "none",
        "TPUMON_COLLECTORS": "accel,serving",
        "TPUMON_FEDERATION_ROLE": "root",
        "TPUMON_FEDERATION_NODE": node,
        "TPUMON_FEDERATION_PEER": "http://127.0.0.1:9",  # patched later
        "TPUMON_FEDERATION_LEASE_S": str(LEASE_S),
        "TPUMON_SERVING_TARGETS": f"http://127.0.0.1:{metrics_port}/metrics",
        "TPUMON_SERVING_INTERVAL_S": str(SERVING_INTERVAL_S),
        "TPUMON_SLOS": json.dumps(SLOS),
        "TPUMON_ACTUATIONS": json.dumps(ACTUATIONS),
    }
    env.update(extra)
    return env


def test_federation_ha_kill_the_root_soak():
    engine = ServingEngine()
    engine.tenant_window_s = 2.0
    metrics_server, mport = start_metrics_server(engine)
    sim = TrafficSim(engine, [
        TenantSpec(name="chat", scenario="chat", rps=6.0, max_new=4),
        TenantSpec(name="rag", scenario="rag", rps=1.0,
                   prompt_chunks=3, max_new=4),
    ], seed=42)

    async def scenario():
        # --- warm the engine outside the judged window (PR 13) -------
        sim.start()
        await wait_until(
            lambda: engine.tenants.get("chat")
            and engine.tenants["chat"].completed >= 3,
            "chat traffic flowing", timeout_s=60.0)
        await wait_until(
            lambda: len(engine._queue) == 0,
            "compile-era queue backlog to drain", timeout_s=60.0)
        await asyncio.sleep(engine.tenant_window_s + 0.5)

        # --- active + standby roots, leases cross-wired --------------
        root_a, srv_a = _mk(**_root_env(
            "rootA", mport, TPUMON_FEDERATION_INITIAL_LEADER="1"))
        root_b, srv_b = _mk(**_root_env("rootB", mport))
        for s in (root_a, root_b):
            assert s.leader is not None and s.actuate is not None
            s.actuate.bind_engine(engine)
        await srv_a.start()
        await srv_b.start()
        a_port, b_port = srv_a.port, srv_b.port
        root_a.leader.peer_url = f"http://127.0.0.1:{b_port}"
        root_b.leader.peer_url = f"http://127.0.0.1:{a_port}"
        await root_a.start()
        await root_b.start()
        await root_a.leader.start()
        await root_b.leader.start()
        # HA steady state: A leads generation 1, B observed it and
        # joined as standby — both via the health heartbeat channel.
        await wait_until(root_a.leader.is_leader, "bootstrap leader")
        await wait_until(
            lambda: root_b.leader.generation == 1
            and not root_b.leader.is_leader(),
            "standby adopts the leader's generation")
        ev_b = await asyncio.to_thread(
            get_json, b_port, "/api/events?kind=leader")
        assert any("joined as standby" in e["msg"] for e in ev_b["events"])

        # --- the tree below: dual-homed aggregator, one leaf ---------
        agg_s, agg_srv = _mk(
            TPUMON_ACCEL_BACKEND="none",
            TPUMON_FEDERATION_ROLE="aggregator",
            TPUMON_FEDERATION_NODE="agg0",
            TPUMON_FEDERATE_UP=(
                f"http://127.0.0.1:{a_port},http://127.0.0.1:{b_port}"),
        )
        agg_s.uplink.backoff_max_s = 0.4
        await agg_srv.start()
        await agg_s.start()
        await agg_s.uplink.start()
        leaf_s, leaf_srv = _mk(
            TPUMON_ACCEL_BACKEND="fake:v5e-8@leaf0",
            TPUMON_FEDERATION_NODE="leaf0",
            TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_srv.port}",
        )
        await leaf_s.start()
        await leaf_s.uplink.start()

        def slices(port):
            try:
                return {
                    s["slice_id"]: s
                    for s in get_json(port, "/api/federation")["slices"]
                }
            except OSError:
                return {}

        def fleet_ok(port):
            def check():
                r = slices(port).get("slice-0")
                return bool(r and r["chips"] == 8 and r["health"] == "ok")
            return check

        await wait_until(fleet_ok(a_port), "fleet view on the active root")
        # The standby takes NO stream while following: its fan-in state
        # will be rebuilt entirely from the failover keyframe.
        assert not await asyncio.to_thread(slices, b_port)

        # End-to-end freshness (ISSUE 19): the active root ages leaf0's
        # samples through agg0's relay, offset-corrected per link.
        def freshness(port):
            try:
                return get_json(port, "/api/federation").get(
                    "freshness") or {}
            except OSError:
                return {}
        await wait_until(
            lambda: "leaf0" in freshness(a_port),
            "leaf freshness accounted on the active root")
        fr_a = (await asyncio.to_thread(freshness, a_port))["leaf0"]
        assert 0 <= fr_a["ms"] < 30_000.0, fr_a

        # --- mid-burn: page fires on BOTH; only the leader sheds -----
        def fast_firing(port):
            return lambda: (
                get_json(port, "/api/slo")["slos"][0]
                ["burn"]["fast"]["firing"])

        def policy_row(port):
            return get_json(port, "/api/actuate")["policies"][0]

        for port in (a_port, b_port):
            await wait_until(
                lambda p=port: (get_json(p, "/api/slo")["slos"][0]
                                ["burn"]["fast"]["long"] == 0.0),
                f"clean baseline on :{port}", timeout_s=60.0)
        sim.degrade(DEGRADE_STALL_S)
        await wait_until(fast_firing(a_port), "page on the active root")
        await wait_until(fast_firing(b_port), "page on the standby")
        await wait_until(
            lambda: policy_row(a_port)["fired"] >= 1,
            "leader's shed fires")
        assert engine.shed_total >= 0 and engine.shed_fractions()
        # The standby's identical policy is armed by the same page but
        # FENCED — before cooldowns, before even dry-run accounting.
        await wait_until(
            lambda: policy_row(b_port)["fenced"] >= 1, "standby fenced")
        row_b = await asyncio.to_thread(policy_row, b_port)
        assert row_b["fired"] == 0
        act_b = await asyncio.to_thread(
            get_json, b_port, "/api/actuate")
        assert act_b["leader"] is False
        ev = await asyncio.to_thread(
            get_json, b_port, "/api/events?kind=actuate")
        assert any(e.get("state") == "fenced" for e in ev["events"])
        # Journal reconciliation: the leader's fired event is mirrored
        # onto the standby by (origin node, origin seq), exactly once.
        await wait_until(
            lambda: any(
                e.get("origin") == "rootA" and e.get("state") == "fired"
                for e in get_json(
                    b_port, "/api/events?kind=actuate")["events"]),
            "leader's actuation mirrored onto the standby")
        ev = await asyncio.to_thread(
            get_json, b_port, "/api/events?kind=actuate")
        mirrored = [(e["origin"], e["origin_seq"]) for e in ev["events"]
                    if e.get("origin")]
        assert len(mirrored) == len(set(mirrored)), "duplicated mirrors"

        # --- kill the active root mid-burn ---------------------------
        t_kill = time.monotonic()
        await srv_a.stop()
        await root_a.stop()
        await wait_until(
            lambda: root_b.leader.is_leader()
            and root_b.leader.generation == 2,
            "standby promotes with a bumped generation")
        await wait_until(fleet_ok(b_port),
                         "fleet view rebuilt on the new leader")
        failover_s = time.monotonic() - t_kill
        assert failover_s <= FAILOVER_BUDGET_S, (
            f"failover took {failover_s:.1f}s "
            f"(budget {FAILOVER_BUDGET_S:.1f}s)")
        # The rotation really was a dual-homed failover + keyframe
        # resync, not a reconnect to the corpse.
        assert agg_s.uplink.url.endswith(str(b_port))
        assert agg_s.uplink.failovers >= 1
        assert agg_s.uplink.enc.stats["keyframes"] >= 2
        # Page still firing; the armed policy the standby inherited
        # fires FOR REAL now — no operator, no re-arm.
        assert await asyncio.to_thread(
            lambda: fast_firing(b_port)())
        await wait_until(
            lambda: policy_row(b_port)["fired"] >= 1,
            "promoted standby sheds for real")
        act_b = await asyncio.to_thread(get_json, b_port, "/api/actuate")
        assert act_b["leader"] is True
        # Leadership is first-class observable: /api/federation block,
        # exporter families, health.
        fed = await asyncio.to_thread(get_json, b_port, "/api/federation")
        assert fed["leader"]["leader"] and fed["leader"]["generation"] == 2
        def metrics_text():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{b_port}/metrics", timeout=5) as r:
                return r.read().decode()
        text = await asyncio.to_thread(metrics_text)
        assert "tpumon_federation_leader 1" in text
        assert "tpumon_federation_generation 2" in text
        assert "tpumon_federation_failovers_total 1" in text
        # Freshness survives the failover: the promoted root re-derives
        # leaf0's age from ITS OWN per-link clock offsets (keyframe
        # resync rebuilt the fan-in) — no negative ages, no multi-hour
        # spikes from trusting the dead root's clock arithmetic.
        await wait_until(
            lambda: "leaf0" in freshness(b_port),
            "leaf freshness re-accounted on the promoted root")
        fr_b = (await asyncio.to_thread(freshness, b_port))["leaf0"]
        assert 0 <= fr_b["ms"] < 30_000.0, fr_b
        text = await asyncio.to_thread(metrics_text)
        assert 'tpumon_federation_freshness_ms{node="leaf0"' in text

        # --- the old root restarts: standby, whatever its flag -------
        root_a2, srv_a2 = _mk(**_root_env(
            "rootA", mport,
            TPUMON_PORT=str(a_port),  # same address B's lease polls
            TPUMON_FEDERATION_INITIAL_LEADER="1",
        ))
        root_a2.actuate.bind_engine(engine)
        root_a2.leader.peer_url = f"http://127.0.0.1:{b_port}"
        for _ in range(40):  # the freed port can linger briefly
            try:
                await srv_a2.start()
                break
            except OSError:
                await asyncio.sleep(0.1)
        else:
            raise AssertionError("old root's port never came free")
        await root_a2.start()
        await root_a2.leader.start()
        await wait_until(
            lambda: root_a2.leader.generation == 2
            and not root_a2.leader.is_leader(),
            "restarted root adopts generation 2 as standby")
        ev = await asyncio.to_thread(
            get_json, a_port, "/api/events?kind=leader")
        assert any("joined as standby" in e["msg"] for e in ev["events"])
        # No fencing violation on rejoin: B keeps the lease untouched,
        # and the rejoined root's still-armed policy is fenced.
        assert root_b.leader.is_leader()
        assert root_b.leader.demotions == 0
        await wait_until(
            lambda: policy_row(a_port)["fenced"] >= 1,
            "rejoined root fenced")
        assert (await asyncio.to_thread(policy_row, a_port))["fired"] == 0

        # --- wedge the leader: the wedged-but-alive regression -------
        root_b.leader.wedge()
        await wait_until(
            lambda: not root_b.leader.is_leader(),
            "wedged leader self-fences within its lease", timeout_s=10.0)
        # B is still ALIVE — health answering, streams flowing — but
        # fenced; the standby observes a reachable non-leader and takes
        # over with the next generation.
        await wait_until(
            lambda: root_a2.leader.is_leader()
            and root_a2.leader.generation == 3,
            "standby takes over from the wedged leader")
        assert not root_b.leader.is_leader()  # never two leaders
        await wait_until(
            lambda: root_b.leader.generation == 3,
            "wedged root adopts the new generation")
        ev = await asyncio.to_thread(
            get_json, b_port, "/api/events?kind=leader")
        assert any("lease expired without renewal" in e["msg"]
                   for e in ev["events"])
        # The wedged root's actuation stays refused; the new leader's
        # engine fires. Two roots never both shed.
        assert (await asyncio.to_thread(
            get_json, b_port, "/api/actuate"))["leader"] is False
        await wait_until(
            lambda: policy_row(a_port)["fired"] >= 1,
            "new leader's shed fires")

        for s, srv in ((leaf_s, leaf_srv), (agg_s, agg_srv),
                       (root_a2, srv_a2), (root_b, srv_b)):
            await s.stop()
            try:
                await srv.stop()
            except Exception:
                pass  # the leaf's server was never started

    try:
        asyncio.run(scenario())
    finally:
        sim.stop()
        metrics_server.shutdown()
        metrics_server.server_close()


# ---------------- satellite: reconnect-stampede jitter ------------------


def test_reconnect_backoff_jitter_spread_over_64_uplinks():
    """64 uplinks losing the same root at the same instant must NOT
    retry in lockstep: after a few decorrelated rounds their delays
    spread across most of the [base, cap] window, every delay respects
    the fleet-safe cap, and no quarter-second bucket holds more than a
    quarter of the fleet."""
    fleet = []
    for i in range(64):
        rng = random.Random(1000 + i)
        d = 0.25  # every uplink's clock starts at the same instant
        for _ in range(4):
            d = decorrelated_jitter(d, base_s=0.25, cap_s=5.0, rng=rng)
        fleet.append(d)
    assert all(0.25 <= d <= 5.0 for d in fleet)
    assert max(fleet) - min(fleet) > 2.0  # spread, not a stampede
    buckets = {}
    for d in fleet:
        buckets[int(d / 0.25)] = buckets.get(int(d / 0.25), 0) + 1
    assert max(buckets.values()) <= 16, buckets
    assert len(buckets) >= 8
    # The cap holds forever, whatever the walk does.
    rng = random.Random(7)
    d = 0.25
    for _ in range(50):
        d = decorrelated_jitter(d, base_s=0.25, cap_s=5.0, rng=rng)
        assert 0.25 <= d <= 5.0


# ---------------- satellite: chaos `partition` verb ---------------------


def test_split_link_faults_grammar():
    """partition targets links only, links take only partition — either
    mismatch fails loudly at startup, and mixed specs split cleanly."""
    import pytest

    coll, link = split_link_faults("partition:uplink:1.0")
    assert not coll and [f.mode for f in link["uplink"]] == ["partition"]
    coll, link = split_link_faults(
        "err:accel:0.2,partition:leader:0.5,slow:serving:10")
    assert set(coll) == {"accel", "serving"} and set(link) == {"leader"}
    assert link["leader"][0].param == 0.5
    with pytest.raises(ValueError):
        split_link_faults("slow:uplink:10")  # links take only partition
    with pytest.raises(ValueError):
        split_link_faults("partition:accel:1.0")  # not a collector mode


def test_chaos_partition_blackholes_live_uplink():
    """partition on a live leaf→aggregator uplink drops frames WITHOUT
    closing the socket: the upstream sees silence (slice dark), not a
    disconnect; healing the link forces a keyframe resync through the
    seq-gap contract."""

    async def scenario():
        agg_s, agg_srv = _mk(
            TPUMON_ACCEL_BACKEND="none",
            TPUMON_FEDERATION_ROLE="aggregator",
            TPUMON_FEDERATION_NODE="agg0",
            TPUMON_FEDERATION_DARK_AFTER_S="0.6",
        )
        await agg_srv.start()
        await agg_s.start()
        leaf_s, _leaf_srv = _mk(
            TPUMON_ACCEL_BACKEND="fake:v5e-8@leaf0",
            TPUMON_FEDERATION_NODE="leaf0",
            TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_srv.port}",
            TPUMON_FEDERATION_DARK_AFTER_S="0.6",
        )
        leaf_s.uplink.backoff_max_s = 0.4
        await leaf_s.start()
        await leaf_s.uplink.start()

        def health():
            rows = get_json(agg_srv.port, "/api/federation")["slices"]
            return {r["slice_id"]: r["health"] for r in rows}

        await wait_until(
            lambda: health().get("slice-0") == "ok", "tree converges")

        # Blackhole: every frame encoded then dropped, socket open.
        leaf_s.uplink.faults = [Fault(mode="partition", param=1.0)]
        await wait_until(
            lambda: leaf_s.uplink.frames_dropped >= 3, "frames dropped")
        assert leaf_s.uplink.connected  # silence, not a disconnect
        await wait_until(
            lambda: health().get("slice-0") == "dark",
            "upstream sees silence as dark")
        resyncs0 = agg_s.federation.nodes["leaf0"].resyncs

        # Heal: the seq gap forces a keyframe resync, view recovers.
        leaf_s.uplink.faults = []
        await wait_until(
            lambda: health().get("slice-0") == "ok", "view recovers")
        await wait_until(
            lambda: agg_s.federation.nodes["leaf0"].resyncs > resyncs0,
            "keyframe resync after heal")

        await leaf_s.stop()
        await agg_s.stop()
        await agg_srv.stop()

    asyncio.run(scenario())


# ---------------- satellite: SSE slow-consumer fan-out ------------------


def test_sse_slow_consumer_dropped_and_resynced():
    """One stalled SSE consumer must not stall the broadcast tick: its
    bounded queue overruns, is cleared, and its next delivered frame is
    a forced keyframe — while a healthy client on the same broadcaster
    keeps receiving every tick."""
    sampler, server = serve()

    async def scenario():
        await sampler.tick_all()
        await server.start()
        port = server.port

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /api/stream HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        while (await asyncio.wait_for(reader.readline(), 5)) not in (
                b"\r\n", b""):
            pass

        async def next_event():
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line.startswith(b"data: "):
                    return json.loads(line[6:])

        first = await next_event()
        assert "key" in first  # immediate keyframe, no tick waited out

        # A synthetic stalled consumer: registered like _stream does,
        # but nothing ever drains its (tiny) queue.
        slow = {"queue": asyncio.Queue(maxsize=2), "ver": -1,
                "since_key": 1, "needs_key": False}
        server._sse_clients[10_000] = slow

        last_epoch = first["epoch"]
        for _ in range(4):
            await sampler.tick_fast()
            ev = await next_event()  # healthy client: never stalled
            assert ev["epoch"] >= last_epoch
            last_epoch = ev["epoch"]
        # maxsize-2 queue over 4 frames: overrun happened, queue was
        # cleared (drop-and-resync), and the post-overrun frame the
        # broadcaster re-enqueued is a forced keyframe.
        assert server.sse_overruns >= 1
        frame = json.loads(await asyncio.wait_for(slow["queue"].get(), 10))
        assert frame["key"]
        assert not slow["needs_key"]
        h = await asyncio.to_thread(get_json, port, "/api/health")
        assert h["http"]["sse_overruns"] >= 1
        assert h["http"]["sse_clients"] == 2

        del server._sse_clients[10_000]
        writer.close()
        await server.stop()
        await sampler.stop()

    asyncio.run(scenario())
