"""Chaos soak (ISSUE 1 acceptance): hang / error / slow / corrupt faults
injected into host, accel, k8s and serving SIMULTANEOUSLY against the
live server — every /api/* route keeps answering within 2x the sample
interval, failing sources go stale and raise ``source-down`` alerts, and
once the faults are lifted the breakers close and the alerts clear.

This is the end-to-end proof of the resilience tentpole: the degraded
modes are driven through the real app wiring (config --chaos ->
collectors.chaos wrappers -> resilience deadlines/breakers -> alerts ->
HTTP), not through unit seams."""

import asyncio
import time
import urllib.request

from tests.fakes import fake_jetstream, fake_k8s_api
from tests.test_k8s import pod_doc
from tests.test_server_api import get_json
from tests.test_serving import JETSTREAM_TEXT
from tpumon.app import build
from tpumon.collectors.chaos import ChaosCollector
from tpumon.config import load_config

SAMPLE_INTERVAL_S = 0.75
ROUTE_BUDGET_S = 2 * SAMPLE_INTERVAL_S

ROUTES = (
    "/",
    "/api/host/metrics",
    "/api/accel/metrics",
    "/api/gpu/metrics",
    "/api/k8s/pods",
    "/api/history",
    "/api/alerts",
    "/api/serving",
    "/api/topology",
    "/api/health",
    "/metrics",
)

# One fault mode per source, all four modes represented: host hangs
# (deadline path), accel errors (breaker path), k8s errors behind the
# real HTTP transport, serving is slow AND lies by omission.
CHAOS_SPEC = (
    "hang:host:1.0,err:accel:1.0,err:k8s:1.0,"
    "slow:serving:120,corrupt:serving:1.0"
)
DOWN_TITLES = {f"Source {s} down" for s in ("host", "accel", "k8s")}


def fetch_timed(port: int, path: str) -> tuple[int, float]:
    t0 = time.monotonic()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=ROUTE_BUDGET_S + 5
    ) as r:
        r.read()
        return r.status, time.monotonic() - t0


async def wait_until(fn, what: str, timeout_s: float = 30.0):
    """Poll ``fn`` (sync, cheap) until truthy while the sampler loops run
    in the background; a bounded soak must fail loudly, never hang."""
    t0 = time.monotonic()
    while True:
        v = fn()
        if v:
            return v
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError(f"soak: timed out waiting for {what}")
        await asyncio.sleep(0.1)


def test_chaos_soak_degrades_and_recovers():
    k8s = fake_k8s_api([pod_doc(name="w0", phase="Running")])
    js = fake_jetstream(JETSTREAM_TEXT)
    cfg = load_config(env={
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_K8S_MODE": "api",
        "TPUMON_K8S_API_URL": k8s.url,
        "TPUMON_SERVING_TARGETS": js.url,
        "TPUMON_SAMPLE_INTERVAL_S": str(SAMPLE_INTERVAL_S),
        "TPUMON_PODS_INTERVAL_S": str(SAMPLE_INTERVAL_S),
        "TPUMON_SERVING_INTERVAL_S": str(SAMPLE_INTERVAL_S),
        "TPUMON_COLLECT_DEADLINE_S": "0.3",
        "TPUMON_BREAKER_FAILURES": "2",
        "TPUMON_BREAKER_BACKOFF_S": "0.3",
        "TPUMON_BREAKER_BACKOFF_MAX_S": "1.0",
        "TPUMON_CHAOS": CHAOS_SPEC,
        "TPUMON_CHAOS_SEED": "42",
    })
    sampler, server = build(cfg)
    # --chaos wrapped exactly the targeted sources.
    for c in (sampler.host, sampler.accel, sampler.k8s, sampler.serving):
        assert isinstance(c, ChaosCollector)

    async def scenario():
        await sampler.start()  # live loops, faults active from tick one
        await server.start()
        port = server.port

        def serious_titles():
            return {
                a["title"] for a in sampler.engine.last.get("serious", [])
            }

        def health():
            return sampler.health_json()["sources"]

        # --- degraded phase -------------------------------------------
        # Failing sources trip their breakers and page as source-down.
        await wait_until(
            lambda: DOWN_TITLES <= serious_titles(),
            f"source-down alerts {DOWN_TITLES}",
        )
        h = health()
        for name in ("host", "accel", "k8s"):
            assert not h[name]["ok"]
            assert h[name]["breaker"]["state"] != "closed"
        assert h["host"]["error"].startswith("deadline exceeded")
        assert "injected error" in h["accel"]["error"]
        assert h["host"]["deadline_exceeded"] >= 2
        # Affected data is stale: the last sample's ts stops advancing.
        assert time.time() - h["k8s"]["ts"] >= 0  # published, with its age
        # Serving stays up but slow+corrupt: collected ok, payload marked.
        assert h["serving"]["ok"]
        assert any("corrupt" in n for n in h["serving"]["notes"])

        # Every route answers within 2x the sample interval, mid-chaos —
        # and the API view itself reports the chaos + degraded sources.
        for path in ROUTES:
            status, dt = await asyncio.to_thread(fetch_timed, port, path)
            assert status == 200, path
            assert dt < ROUTE_BUDGET_S, f"{path} took {dt:.2f}s under chaos"
        api_health = await asyncio.to_thread(get_json, port, "/api/health")
        assert api_health["chaos"] == CHAOS_SPEC
        assert not api_health["sources"]["host"]["ok"]
        alerts = await asyncio.to_thread(get_json, port, "/api/alerts")
        assert DOWN_TITLES <= {a["title"] for a in alerts["serious"]}
        metrics = await asyncio.to_thread(
            lambda: urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
        )
        assert 'tpumon_collect_deadline_exceeded_total{source="host"}' in metrics
        assert 'tpumon_source_breaker_state{source="accel"}' in metrics

        # --- recovery phase -------------------------------------------
        for c in (sampler.host, sampler.accel, sampler.k8s, sampler.serving):
            c.set_faults([])
        await wait_until(
            lambda: not (DOWN_TITLES & serious_titles()),
            "source-down alerts to clear",
        )
        await wait_until(
            lambda: all(
                s["ok"] and s.get("breaker", {}).get("state", "closed") == "closed"
                for s in health().values()
            ),
            "all sources healthy with closed breakers",
        )
        for path in ROUTES:
            status, dt = await asyncio.to_thread(fetch_timed, port, path)
            assert status == 200 and dt < ROUTE_BUDGET_S, path
        # The watchdogs saw the whole soak without a swallowed-exception
        # storm: chaos faults degrade samples, they don't crash loops.
        loops = sampler.health_json()["loops"]
        assert loops["fast"]["ticks"] > 0
        assert loops["fast"]["consecutive_exceptions"] == 0

        await server.stop()
        await sampler.stop()

    try:
        asyncio.run(scenario())
    finally:
        k8s.close()
        js.close()
