"""The committed docs/dashboard.svg is produced by executing the real
frontend (chartcore.js + dashboard.js under jsmini, driven by real
server payloads) — this proves the producer script stays runnable and
keeps emitting every section of the page (the analogue of the
reference's screenshot.png staying truthful)."""

from __future__ import annotations

import importlib.util
import os


def _load_tool():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "render_dashboard.py")
    spec = importlib.util.spec_from_file_location("render_dashboard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_full_page_artifact_renders():
    svg = _load_tool().render()
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    # Every dashboard section made it into the page.
    for marker in ("HOST CPU", "TPU CHIPS", "ICI TOPOLOGY", "SERVING",
                   "TRAINING", "KUBERNETES TPU PODS", "ACTIVE ALERTS (MODAL)"):
        assert marker in svg, marker
    # Executed-content spot checks: chip grid cells, pod badge text, and
    # alert title all flowed through dashboard.js, not a mockup.
    assert svg.count("% MXU") >= 8
    assert "Failed · OOMKilled" in svg
    assert "HBM pressure on tpu-host-0/chip-2" in svg
    # No un-rendered sentinel leaked into the picture.
    for bad in ("NaN", "undefined", "None"):
        assert bad not in svg
