"""Resilience layer (tpumon.resilience + tpumon.collectors.chaos +
crash-safe history): the degraded modes SURVEY §7 promises, now
exercised — hung collectors bounded by deadlines, repeated failures
tripping circuit breakers, loop exceptions counted, history surviving
restarts, and every fault injectable on demand."""

import asyncio
import json
import random
import time

import pytest

from tpumon.collectors import Sample, run_collector
from tpumon.collectors.chaos import (
    ChaosCollector,
    ChaosError,
    Fault,
    parse_chaos_spec,
    wrap_collectors,
)
from tpumon.config import load_config
from tpumon.history import HistorySnapshotter, RingHistory
from tpumon.resilience import (
    DEADLINE_ERROR,
    CircuitBreaker,
    DeadlineExceeded,
    LoopWatchdog,
    collect_bounded,
)
from tpumon.sampler import Sampler


class FakeCollector:
    """Scripted collector: hangs, raises, or returns per call."""

    def __init__(self, name="fake", hang_s=0.0, error=None, data=None,
                 swallow_cancel=False):
        self.name = name
        self.hang_s = hang_s
        self.error = error
        self.data = data if data is not None else {"v": 1}
        self.swallow_cancel = swallow_cancel
        self.calls = 0
        self.cancelled = 0

    async def collect(self) -> Sample:
        self.calls += 1
        if self.hang_s:
            try:
                await asyncio.sleep(self.hang_s)
            except asyncio.CancelledError:
                self.cancelled += 1
                if not self.swallow_cancel:
                    raise
                await asyncio.sleep(self.hang_s)  # wedged: ignores cancel
        if self.error is not None:
            raise RuntimeError(self.error)
        return Sample(source=self.name, ok=True, data=self.data)


def sampler_cfg(**env):
    base = {"TPUMON_COLLECTORS": "host,accel", "TPUMON_K8S_MODE": "none"}
    base.update(env)
    return load_config(env=base)


# ------------------------------ deadlines ------------------------------

def test_collect_bounded_returns_at_deadline():
    c = FakeCollector(hang_s=30.0)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        asyncio.run(collect_bounded(c, deadline_s=0.05))
    assert time.monotonic() - t0 < 1.0


def test_collect_bounded_unblocks_even_if_cancel_is_swallowed():
    """bare asyncio.wait_for awaits the cancellation, so a task that
    swallows CancelledError hangs the caller anyway; collect_bounded
    must return at the deadline regardless."""
    c = FakeCollector(hang_s=30.0, swallow_cancel=True)

    async def run():
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            await collect_bounded(c, deadline_s=0.05)
        return time.monotonic() - t0

    assert asyncio.run(run()) < 1.0


def test_collect_bounded_passthrough_and_own_exception():
    ok = asyncio.run(collect_bounded(FakeCollector(), deadline_s=5.0))
    assert ok.ok and ok.data == {"v": 1}
    with pytest.raises(RuntimeError):
        asyncio.run(collect_bounded(FakeCollector(error="boom"), deadline_s=5.0))


def test_run_collector_degrades_on_deadline():
    c = FakeCollector(name="k8s", hang_s=30.0)
    s = asyncio.run(run_collector(c, deadline_s=0.05))
    assert not s.ok and s.source == "k8s"
    assert s.error.startswith(DEADLINE_ERROR)
    assert s.latency_ms < 1000
    # The orphan was cancelled, not leaked to run forever.
    assert c.cancelled == 1


def test_run_collector_without_deadline_unchanged():
    s = asyncio.run(run_collector(FakeCollector()))
    assert s.ok


def test_collect_bounded_reaps_orphan_when_caller_cancelled():
    """Sampler shutdown mid-collect: cancelling the caller must also
    cancel the in-flight collect (asyncio.wait doesn't), or a hung
    collector outlives the sampler."""
    c = FakeCollector(hang_s=30.0)

    async def run():
        caller = asyncio.create_task(collect_bounded(c, deadline_s=10.0))
        await asyncio.sleep(0.02)  # let the collect start
        caller.cancel()
        with pytest.raises(asyncio.CancelledError):
            await caller
        await asyncio.sleep(0.02)  # let the orphan's cancellation land
        assert c.cancelled == 1

    asyncio.run(run())


def test_hung_collector_does_not_stall_tick_fast_or_other_sources():
    """The tentpole's core claim: a collect() that never returns degrades
    within the configured deadline and the OTHER source still samples on
    this very tick."""
    cfg = sampler_cfg(TPUMON_COLLECT_DEADLINE_S="0.1")
    hung = FakeCollector(name="host", hang_s=60.0)
    fast = FakeCollector(name="accel", data=[])
    sampler = Sampler(cfg, host=hung, accel=fast)

    async def run():
        t0 = time.monotonic()
        await sampler.tick_fast()
        return time.monotonic() - t0

    elapsed = asyncio.run(run())
    # Deadline 0.1s + slack, NOT the 60s hang — that is the claim. The
    # old 1.0s bound flaked under full-suite load (CHANGES.md, PR 7):
    # the event loop itself gets starved, which is scheduler pressure,
    # not a deadline failure. 5s still refutes the hang by an order of
    # magnitude while absorbing a loaded box.
    assert elapsed < 5.0  # deadline 0.1 s + slack, not 60 s
    assert not sampler.latest["host"].ok
    assert sampler.latest["host"].error.startswith(DEADLINE_ERROR)
    assert sampler.latest["accel"].ok and fast.calls == 1
    assert sampler.stats["host"].deadline_exceeded == 1


def test_per_source_deadline_override():
    cfg = sampler_cfg(
        TPUMON_COLLECT_DEADLINE_S="30",
        TPUMON_COLLECT_DEADLINES='{"host": 0.05}',
    )
    sampler = Sampler(cfg, host=FakeCollector(name="host", hang_s=60.0))
    assert sampler._deadline_for("host") == 0.05
    assert sampler._deadline_for("accel") == 30.0
    asyncio.run(sampler.tick_fast())
    assert sampler.latest["host"].error.startswith(DEADLINE_ERROR)


def test_wedged_orphan_caps_at_one_outstanding_collect():
    """Cancellation cannot interrupt a thread wedged in blocking I/O, so
    each abandoned collect can pin a shared-executor thread. While a
    source's previous orphan is still alive, new polls are refused — a
    wedged source holds at most ONE thread (not one per probe) and polls
    resume once the orphan finally dies."""
    cfg = sampler_cfg(
        TPUMON_COLLECT_DEADLINE_S="0.05", TPUMON_BREAKER_FAILURES="0"
    )
    wedged = FakeCollector(name="host", hang_s=0.3, swallow_cancel=True)
    sampler = Sampler(cfg, host=wedged)

    async def run():
        await sampler.tick_fast()  # deadline hit; orphan still wedged
        await sampler.tick_fast()  # refused: orphan outstanding
        assert wedged.calls == 1
        assert sampler.stats["host"].failures == 2
        assert "wedged" in sampler.latest["host"].error
        await asyncio.sleep(0.4)  # the wedged orphan finally dies
        await sampler.tick_fast()  # orphan reaped: polls resume
        assert wedged.calls == 2

    asyncio.run(run())
    assert sampler.latest["host"].error.startswith(DEADLINE_ERROR)


# ------------------------------ breaker --------------------------------

def clocked_breaker(**kw):
    now = [1000.0]
    kw.setdefault("jitter_frac", 0.0)
    br = CircuitBreaker(clock=lambda: now[0], **kw)
    return br, now


def test_breaker_full_lifecycle_closed_open_half_open_closed():
    br, now = clocked_breaker(failure_threshold=3, base_backoff_s=5.0)
    assert br.state == "closed" and br.allow()
    for _ in range(2):
        br.record(False)
    assert br.state == "closed"  # below threshold
    br.record(False)
    assert br.state == "open" and br.opened_count == 1
    assert not br.allow()
    assert br.retry_in_s() == pytest.approx(5.0)
    now[0] += 5.1
    assert br.allow()  # backoff elapsed: this call is the probe
    assert br.state == "half_open"
    assert not br.allow()  # probe outstanding: nothing else admitted
    br.record(True)
    assert br.state == "closed" and br.allow()
    assert br.consecutive_failures == 0


def test_breaker_failed_probe_doubles_backoff_to_cap():
    br, now = clocked_breaker(
        failure_threshold=1, base_backoff_s=4.0, max_backoff_s=10.0
    )
    br.record(False)
    assert br.state == "open"
    for expect in (8.0, 10.0, 10.0):  # doubled, then capped
        now[0] += 60
        assert br.allow()
        br.record(False)
        assert br.state == "open"
        assert br.retry_in_s() == pytest.approx(expect)


def test_breaker_jitter_spreads_probes():
    rng = random.Random(7)
    br = CircuitBreaker(failure_threshold=1, base_backoff_s=100.0,
                        jitter_frac=0.2, clock=lambda: 0.0, rng=rng)
    br.record(False)
    retry = br.retry_in_s()
    assert 80.0 <= retry <= 120.0 and retry != 100.0


def test_breaker_json_shape():
    br, now = clocked_breaker(failure_threshold=1)
    br.record(False)
    d = br.to_json()
    assert d["state"] == "open" and d["opened_count"] == 1
    assert d["retry_in_s"] >= 0


def test_sampler_breaker_skips_polls_while_open():
    """An open breaker suppresses the poll entirely — the dead collector
    is not invoked (no deadline budget burned), the skip is counted, and
    the source recovers once the collector does."""
    cfg = sampler_cfg(
        TPUMON_BREAKER_FAILURES="2", TPUMON_BREAKER_BACKOFF_S="60"
    )
    bad = FakeCollector(name="host", error="dead")
    sampler = Sampler(cfg, host=bad)

    async def run():
        for _ in range(5):
            await sampler.tick_fast()

    asyncio.run(run())
    br = sampler.breakers["host"]
    assert br.state == "open"
    assert bad.calls == 2  # polls 3..5 suppressed
    assert sampler.stats["host"].skipped == 3
    # Backoff elapsed -> half-open probe; collector healthy -> closed.
    br._next_probe = 0.0
    bad.error = None
    asyncio.run(sampler.tick_fast())
    assert br.state == "closed" and sampler.latest["host"].ok


def test_sampler_breaker_disabled_with_zero_failures():
    cfg = sampler_cfg(TPUMON_BREAKER_FAILURES="0")
    bad = FakeCollector(name="host", error="dead")
    sampler = Sampler(cfg, host=bad)

    async def run():
        for _ in range(4):
            await sampler.tick_fast()

    asyncio.run(run())
    assert sampler.breakers == {} and bad.calls == 4


# ------------------------- source-down alerting ------------------------

def test_source_down_alert_fires_and_clears():
    cfg = sampler_cfg(
        TPUMON_BREAKER_FAILURES="2", TPUMON_BREAKER_BACKOFF_S="60"
    )
    bad = FakeCollector(name="host", error="connection refused")
    sampler = Sampler(cfg, host=bad, accel=FakeCollector(name="accel", data=[]))

    async def ticks(n):
        for _ in range(n):
            await sampler.tick_fast()

    asyncio.run(ticks(3))
    serious = sampler.engine.last["serious"]
    down = [a for a in serious if a["title"] == "Source host down"]
    assert len(down) == 1
    assert "connection refused" in down[0]["desc"]
    # Recovery: breaker re-probes, closes, and the alert clears.
    sampler.breakers["host"]._next_probe = 0.0
    bad.error = None
    asyncio.run(ticks(1))
    assert not [
        a for a in sampler.engine.last["serious"]
        if a["title"] == "Source host down"
    ]


def test_source_health_shape():
    cfg = sampler_cfg()
    sampler = Sampler(cfg, host=FakeCollector(name="host"))
    asyncio.run(sampler.tick_fast())
    (h,) = sampler.source_health()
    assert h == {
        "source": "host", "ok": True, "error": None,
        "consecutive_failures": 0, "breaker": "closed",
    }


# ------------------------------ watchdog -------------------------------

def test_loop_watchdog_counts_lag_and_exceptions():
    wd = LoopWatchdog(name="fast", interval_s=1.0)
    wd.tick(0.5)
    wd.tick(1.5)  # overran its interval
    wd.tick(0.2, error="ValueError: boom")
    wd.tick(0.2, error="ValueError: again")
    d = wd.to_json()
    assert d["ticks"] == 4 and d["lagged_ticks"] == 1
    assert d["max_lag_s"] == pytest.approx(0.5)
    assert d["exceptions"] == 2 and d["consecutive_exceptions"] == 2
    assert d["last_error"] == "ValueError: again"
    wd.tick(0.2)
    assert wd.consecutive_exceptions == 0 and wd.exceptions == 2


def test_sampler_loop_surfaces_swallowed_exceptions():
    """The old ``except Exception: pass`` is now accounted: a pipeline
    bug (not a collector failure) shows in the watchdog."""
    cfg = sampler_cfg(TPUMON_SAMPLE_INTERVAL_S="0.01")
    sampler = Sampler(cfg, host=FakeCollector(name="host"))
    sampler._record_history = lambda ts: (_ for _ in ()).throw(
        RuntimeError("pipeline bug")
    )

    async def run():
        task = asyncio.create_task(
            sampler._loop(sampler.tick_fast, 0.01, "fast")
        )
        for _ in range(200):
            await asyncio.sleep(0.01)
            if sampler.watchdogs["fast"].exceptions:
                break
        task.cancel()

    asyncio.run(run())
    wd = sampler.watchdogs["fast"]
    assert wd.exceptions >= 1
    assert "pipeline bug" in wd.last_error
    assert "fast" in sampler.health_json()["loops"]


# ------------------------------- chaos ---------------------------------

def test_parse_chaos_spec():
    spec = parse_chaos_spec("hang:accel:0.1, err:k8s:0.3,slow:host:200")
    assert spec["accel"] == [Fault("hang", 0.1)]
    assert spec["k8s"] == [Fault("err", 0.3)]
    assert spec["host"] == [Fault("slow", 200.0)]


@pytest.mark.parametrize("bad", [
    "hang:accel",            # missing param
    "explode:accel:0.1",     # unknown mode
    "err:accel:lots",        # non-numeric param
    "err:accel:1.5",         # probability > 1
    "slow:accel:-5",         # negative
])
def test_parse_chaos_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_chaos_spec(bad)


def test_wrap_collectors_targets_and_rejects_typos():
    host, accel = FakeCollector(name="host"), FakeCollector(name="accel")
    out = wrap_collectors(
        {"host": host, "accel": accel, "k8s": None}, "err:host:1.0"
    )
    assert isinstance(out["host"], ChaosCollector)
    assert out["accel"] is accel and out["k8s"] is None
    with pytest.raises(ValueError):
        wrap_collectors({"host": host}, "err:hots:1.0")
    # A valid source whose collector is disabled (None) must also raise:
    # the fault would silently inject nothing.
    with pytest.raises(ValueError, match="disabled"):
        wrap_collectors({"host": host, "k8s": None}, "err:k8s:1.0")


def test_chaos_err_and_slow_faults():
    inner = FakeCollector(name="host")
    c = ChaosCollector(inner=inner, faults=[Fault("err", 1.0)], seed=1)
    with pytest.raises(ChaosError):
        asyncio.run(c.collect())
    c.set_faults([Fault("slow", 50.0)])
    t0 = time.monotonic()
    s = asyncio.run(c.collect())
    assert s.ok and time.monotonic() - t0 >= 0.05


def test_chaos_hang_degrades_via_deadline():
    c = ChaosCollector(
        inner=FakeCollector(name="host"), faults=[Fault("hang", 1.0)]
    )
    s = asyncio.run(run_collector(c, deadline_s=0.05))
    assert not s.ok and s.error.startswith(DEADLINE_ERROR)


def test_chaos_corrupt_drops_never_invents():
    inner = FakeCollector(
        name="k8s",
        data=[{"name": f"p{i}", "phase": "Running", "restarts": 0}
              for i in range(8)],
    )
    c = ChaosCollector(inner=inner, faults=[Fault("corrupt", 1.0)], seed=3)
    s = asyncio.run(c.collect())
    assert s.ok  # corrupt payloads still report ok: the lie is in data
    assert "chaos: payload corrupted" in s.notes
    orig = {json.dumps(d, sort_keys=True) for d in inner.data}
    for d in s.data:
        assert set(d) < {"name", "phase", "restarts"} or (
            json.dumps(d, sort_keys=True) in orig
        )
    assert len(s.data) <= len(inner.data)


def test_chaos_flap_drives_breaker_open_half_open_closed():
    """A flapping source exercises the breaker's whole lifecycle: errors
    trip it open, the half-open probe during a healthy phase closes it."""
    cfg = sampler_cfg(
        TPUMON_BREAKER_FAILURES="2", TPUMON_BREAKER_BACKOFF_S="60"
    )
    chaos = ChaosCollector(
        inner=FakeCollector(name="host"), faults=[Fault("flap", 0.3)], seed=11
    )
    sampler = Sampler(cfg, host=chaos)

    async def ticks(n):
        for _ in range(n):
            await sampler.tick_fast()

    seen = set()

    def settle(deadline_states, n=40):
        for _ in range(n):
            asyncio.run(ticks(1))
            br = sampler.breakers.get("host")
            if br is None:
                continue
            seen.add(br.state)
            if br.state in deadline_states:
                return br
        raise AssertionError(
            f"breaker never reached {deadline_states}; saw {seen}"
        )

    settle({"open"})
    # Force the probe due, then stop flapping: probe succeeds -> closed.
    sampler.breakers["host"]._next_probe = 0.0
    chaos.set_faults([])
    settle({"closed"}, n=5)
    assert {"open", "closed"} <= seen


# ------------------------ crash-safe history ---------------------------

def make_ring(n_fine=20, coarse_pairs=()):
    ring = RingHistory(window_s=1800, long_window_s=24 * 3600)
    now = time.time()
    for i in range(n_fine):
        ring.record("cpu", 50.0 + i, ts=now - (n_fine - i) * 30)
        ring.record("mxu", 10.0 + i, ts=now - (n_fine - i) * 30)
    for t, v in coarse_pairs:
        ring.restore_coarse("cpu", [(t, v)])
    return ring


def test_history_snapshot_restore_round_trip(tmp_path):
    path = str(tmp_path / "hist.json")
    ring = make_ring()
    assert HistorySnapshotter(ring, path).save()

    fresh = RingHistory(window_s=1800, long_window_s=24 * 3600)
    snap = HistorySnapshotter(fresh, path)
    assert snap.restore()
    assert [v for _, v in fresh.series["cpu"].points] == [
        v for _, v in ring.series["cpu"].points
    ]
    assert [v for _, v in fresh.series["mxu"].points] == [
        v for _, v in ring.series["mxu"].points
    ]
    # The restored ring serves /api/history's ring path.
    out = fresh.snapshot_series("cpu", step_s=30)
    assert out["data"]  # non-empty after restart


def test_history_snapshot_restores_coarse_tier(tmp_path):
    path = str(tmp_path / "hist.json")
    now = time.time()
    # Coarse points hours old (outside the fine window, inside the long
    # one) plus fresh fine points.
    ring = make_ring(coarse_pairs=[(now - 7200, 33.0), (now - 3600, 44.0)])
    HistorySnapshotter(ring, path).save()
    fresh = RingHistory(window_s=1800, long_window_s=24 * 3600)
    assert HistorySnapshotter(fresh, path).restore()
    assert (pytest.approx(33.0), pytest.approx(44.0)) == tuple(
        v for _, v in list(fresh.series["cpu"].coarse)[:2]
    )


def test_history_snapshot_rejects_corrupt_missing_stale(tmp_path):
    ring = RingHistory(window_s=1800)
    missing = HistorySnapshotter(ring, str(tmp_path / "nope.json"))
    assert not missing.restore()

    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    assert not HistorySnapshotter(ring, str(corrupt)).restore()

    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({
        "version": 1, "saved_at": time.time() - 48 * 3600,
        "points": {"cpu": [[time.time(), 1.0]]}, "coarse": {},
    }))
    assert not HistorySnapshotter(ring, str(stale)).restore()
    assert ring.series == {} or not ring.series.get("cpu")

    wrong_version = tmp_path / "v99.json"
    wrong_version.write_text(json.dumps(
        {"version": 99, "saved_at": time.time(), "points": {}, "coarse": {}}
    ))
    assert not HistorySnapshotter(ring, str(wrong_version)).restore()


def test_history_snapshot_staleness_tracks_long_window(tmp_path):
    """The staleness cutoff is the ring's configured long window, not a
    fixed day: a 72 h ring keeps a 30 h-old snapshot's coarse tier."""
    now = time.time()
    state = json.dumps({
        "version": 1, "saved_at": now - 30 * 3600,
        "points": {},
        "coarse": {"cpu": [[now - 30 * 3600, 55.0]]},
    })
    path = tmp_path / "hist.json"
    path.write_text(state)
    wide = RingHistory(window_s=1800, long_window_s=72 * 3600)
    assert HistorySnapshotter(wide, str(path)).restore()
    assert list(wide.series["cpu"].coarse)
    narrow = RingHistory(window_s=1800, long_window_s=24 * 3600)
    assert not HistorySnapshotter(narrow, str(path)).restore()


def test_history_survives_sampler_stop_start_cycle(tmp_path):
    """Acceptance: restored ring + coarse points are served by
    /api/history after a monitor restart (app wiring: build ->
    snapshotter.restore() when no full state snapshot restored)."""
    from tpumon.app import build
    from tpumon.history import HistoryService

    path = str(tmp_path / "hist.json")
    env = {
        "TPUMON_PORT": "0",
        "TPUMON_HOST": "127.0.0.1",
        "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
        "TPUMON_K8S_MODE": "none",
        "TPUMON_COLLECTORS": "host,accel",
        "TPUMON_HISTORY_SNAPSHOT_PATH": path,
    }

    async def first_life():
        sampler, _server = build(load_config(env=env))
        for _ in range(3):
            await sampler.tick_fast()
        snap = HistorySnapshotter(sampler.history, path)
        await snap.save_async()
        return dict(sampler.history.dump_points())

    saved = asyncio.run(first_life())
    assert saved["cpu"] and saved["mxu"]

    async def second_life():
        sampler, _server = build(load_config(env=env))
        snap = HistorySnapshotter(sampler.history, path)
        assert snap.restore()
        return await HistoryService(sampler.history, None).snapshot()

    out = asyncio.run(second_life())
    assert out["source"] == "ring"
    assert out["cpu"]["data"] and out["mxu"]["data"]


def test_snapshotter_periodic_loop_and_final_save(tmp_path):
    path = str(tmp_path / "hist.json")
    ring = make_ring(n_fine=4)
    snap = HistorySnapshotter(ring, path, interval_s=0.02)

    async def run():
        await snap.start()
        for _ in range(100):
            await asyncio.sleep(0.02)
            if snap.last_save_ts is not None:
                break
        await snap.stop()

    asyncio.run(run())
    assert snap.last_save_ts is not None and snap.last_error is None
    # v2 binary format on disk (magic header), restorable round-trip.
    from tpumon import tsdb

    with open(path, "rb") as f:
        assert f.read(len(tsdb.MAGIC)) == tsdb.MAGIC
    fresh = RingHistory(window_s=1800, long_window_s=24 * 3600)
    assert HistorySnapshotter(fresh, path).restore()
    assert [v for _, v in fresh.series["cpu"].points] == [
        v for _, v in ring.series["cpu"].points
    ]
    # The idle loop skipped rewrites once the ring stopped changing.
    assert snap.saves >= 1 and snap.skipped_unchanged >= 1


# ---------------------------- observability ----------------------------

def test_health_and_exporter_surface_resilience_state():
    from tpumon.exporter import render_exporter

    cfg = sampler_cfg(
        TPUMON_BREAKER_FAILURES="2", TPUMON_BREAKER_BACKOFF_S="60",
        TPUMON_COLLECT_DEADLINE_S="0.05",
    )
    sampler = Sampler(
        cfg,
        host=FakeCollector(name="host", hang_s=60.0),
        accel=FakeCollector(name="accel", data=[]),
    )

    async def run():
        for _ in range(3):
            await sampler.tick_fast()
        sampler.watchdogs["fast"] = LoopWatchdog(name="fast", interval_s=1.0)
        sampler.watchdogs["fast"].tick(2.0, error="RuntimeError: x")

    asyncio.run(run())
    health = sampler.health_json()
    host = health["sources"]["host"]
    assert host["breaker"]["state"] == "open"
    assert host["deadline_exceeded"] >= 2
    assert health["loops"]["fast"]["exceptions"] == 1

    text = render_exporter(sampler)
    assert 'tpumon_collect_deadline_exceeded_total{source="host"}' in text
    assert 'tpumon_source_breaker_state{source="host"} 2' in text
    assert 'tpumon_source_breaker_opened_total{source="host"}' in text
    assert 'tpumon_loop_exceptions_total{loop="fast"}' in text
    assert 'tpumon_loop_max_lag_seconds{loop="fast"}' in text
