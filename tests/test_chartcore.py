"""EXECUTES the dashboard's chart/topology logic (VERDICT r1 weak #3).

tpumon/web/chartcore.js — the file the browser actually loads — is run
here under tests/jsmini.py (no JS engine exists in this environment;
jsmini is the in-repo interpreter for chartcore's restricted dialect).
A thrown TypeError anywhere in the chart engine fails these tests; the
draw sequence is asserted against a recording canvas; the same
machinery renders docs/dashboard.svg (tools/render_dashboard.py), this
repo's analogue of the reference's screenshot.png artifact.
"""

from __future__ import annotations

import os

import pytest

from tests.canvas2d import RecordingCtx, ops_to_svg
from tests.jsmini import UNDEF, JsError, load

CHARTCORE = os.path.join(
    os.path.dirname(__file__), "..", "tpumon", "web", "chartcore.js"
)


@pytest.fixture(scope="module")
def js():
    with open(CHARTCORE) as f:
        return load(f.read())


GEOM = {"w": 600.0, "h": 190.0, "l": 44.0, "r": 10.0, "t": 8.0, "b": 20.0}
SERIES = [
    {"label": "MXU duty %", "color": "#36d399", "fill": True},
    {"label": "HBM %", "color": "#22d3ee"},
]


# ----------------------------------------------------------- formatters

def test_formatters(js):
    assert js.call("fmtPct", None) == "–"
    # (42.35).toFixed(1) === "42.4" in real JS too (binary repr rounds up)
    assert js.call("fmtPct", 42.35) == "42.4%"
    assert js.call("fmtPct", 42.34) == "42.3%"
    assert js.call("fmtGiB", None) == "–"
    assert js.call("fmtGiB", 16 * 2.0**30) == "16.0 GiB"
    assert js.call("fmtBps", None) == "–"
    assert js.call("fmtBps", 0.0) == "0.0 B/s"
    assert js.call("fmtBps", 999.0) == "999.0 B/s"
    assert js.call("fmtBps", 2.5e9) == "2.5 GB/s"
    assert js.call("fmtBps", 7.2e13) == "72.0 TB/s"


def test_chart_fmt_y(js):
    assert js.call("chartFmtY", 85.0, "%") == "85%"
    assert js.call("chartFmtY", 1.5e6, "bps") == "1.5 MB/s"
    assert js.call("chartFmtY", 2500.0, UNDEF) == "2.5k"
    assert js.call("chartFmtY", 12.5, UNDEF) == "12.5"
    assert js.call("chartFmtY", 12.0, UNDEF) == "12"


# --------------------------------------------------------------- domain

def test_domain_fixed_and_auto(js):
    assert js.call("chartDomain", [[10.0, 50.0]], 100.0) == [0, 100]
    lo, hi = js.call("chartDomain", [[10.0, 40.0], [2.0]], UNDEF)
    assert lo == 0 and abs(hi - 46.0) < 1e-9  # 40 * 1.15
    # Empty / non-finite data still yields a drawable domain (max
    # falls back to 1, then gets the same 1.15 headroom).
    assert js.call("chartDomain", [[]], UNDEF) == [0, 1.15]
    assert js.call("chartDomain", [[float("nan")]], UNDEF) == [0, 1.15]


def test_xy_geometry(js):
    dom = [0.0, 100.0]
    x0, y0 = js.call("chartXY", GEOM, 0.0, 0.0, 10.0, dom)
    assert x0 == GEOM["l"]
    assert y0 == GEOM["h"] - GEOM["b"]  # v=0 sits on the baseline
    x1, y1 = js.call("chartXY", GEOM, 9.0, 100.0, 10.0, dom)
    assert x1 == GEOM["w"] - GEOM["r"]
    assert y1 == GEOM["t"]  # v=max at the top
    # Single point centers at the left margin without dividing by zero.
    xs, _ = js.call("chartXY", GEOM, 0.0, 50.0, 1.0, dom)
    assert xs == GEOM["l"]


def test_x_label_step(js):
    assert js.call("chartXStep", 5.0) == 1
    assert js.call("chartXStep", 60.0) == 9  # ceil(60/7)


# ----------------------------------------------------------------- draw

def test_chart_draw_sequence(js):
    ctx = RecordingCtx()
    labels = [f"10:{i:02d}" for i in range(10)]
    data = [[float(10 * i % 70) for i in range(10)],
            [50.0] * 10]
    res = js.call("chartDraw", ctx.js(), GEOM, labels, data, SERIES,
                  {"yMax": 100.0, "unit": "%"})
    assert res["dom"] == [0, 100] and res["n"] == 10
    # 5 grid lines + their tick labels.
    texts = [op[1][0] for op in ctx.calls("fillText")]
    for tick in ("0%", "25%", "50%", "75%", "100%"):
        assert tick in texts
    # Sparse x labels: step ceil(10/7)=2 -> 5 labels.
    assert sum(1 for t in texts if t.startswith("10:")) == 5
    # Two series drawn: moveTo count = 5 grid + 2 series = 7.
    assert len(ctx.calls("moveTo")) == 7
    # Filled series closes its area path exactly once (series 2 no fill).
    assert len(ctx.calls("closePath")) == 1
    fills = ctx.calls("fill")
    assert len(fills) == 1 and fills[0][2]["globalAlpha"] == 0.12


def test_chart_draw_empty_data_still_renders_axes(js):
    ctx = RecordingCtx()
    res = js.call("chartDraw", ctx.js(), GEOM, [], [[], []], SERIES, {})
    assert res["n"] == 0
    assert len(ctx.calls("stroke")) == 5  # grid only, no crash


def test_chart_draw_type_error_fails(js):
    """The point of executing the JS: a runtime TypeError surfaces as a
    test failure instead of shipping broken to every user."""
    with pytest.raises(JsError, match="TypeError"):
        js.call("chartDraw", UNDEF, GEOM, [], [[]], SERIES, {})
    with pytest.raises(JsError, match="TypeError"):
        # series entry without data array behind it
        js.call("chartDraw", RecordingCtx().js(), GEOM, ["a"],
                UNDEF, SERIES, {})


# -------------------------------------------------------------- tooltip

def test_tip_index(js):
    # px at the left margin -> index 0; at the right edge -> n-1.
    assert js.call("chartTipIndex", GEOM["l"], GEOM, 10.0) == 0
    assert js.call("chartTipIndex", GEOM["w"] - GEOM["r"], GEOM, 10.0) == 9
    assert js.call("chartTipIndex", -500.0, GEOM, 10.0) == -1
    assert js.call("chartTipIndex", 5000.0, GEOM, 10.0) == -1


def test_tip_rows_skip_null_and_nan(js):
    data = [[42.0], [None]]
    html = js.call("chartTipRows", SERIES, data, 0.0, {"unit": "%"})
    assert "MXU duty %: 42%" in html
    assert "HBM" not in html  # null row skipped
    html = js.call("chartTipRows", SERIES, [[float("nan")], [7.0]], 0.0, {})
    assert "MXU" not in html and "HBM %: 7" in html
    assert "#22d3ee" in html


# ------------------------------------------------------------- topology

def chip(i, slice_id="slice-0", **kw):
    base = {
        "chip": f"h/chip-{i}", "slice": slice_id, "index": float(i),
        "coords": [float(i % 4), float(i // 4)], "mxu_duty_pct": 50.0,
        "hbm_pct": 60.0, "tx_bps": 1e9,
    }
    base.update(kw)
    return base


def test_duty_color(js):
    assert js.call("dutyColor", None) == "#2a3550"
    assert js.call("dutyColor", 0.0) == "hsl(210 75% 52%)"
    assert js.call("dutyColor", 100.0) == "hsl(40 75% 52%)"
    assert js.call("dutyColor", 200.0) == "hsl(40 75% 52%)"  # clamped


def test_chip_ring_color(js):
    assert js.call("chipRingColor", chip(0)) == "#0c1220"
    assert js.call("chipRingColor", chip(0, ici_link_up=False)) == "#ef4444"
    assert js.call("chipRingColor", chip(0, ici_link_health=7.0)) == "#f59e0b"


def test_topo_layout_coords_and_fallback(js):
    chips = [chip(i) for i in range(8)]
    pos = js.call("topoLayout", chips)
    assert pos == [[i % 4, i // 4] for i in range(8)]
    # Colliding coords -> index grid fallback.
    collide = [chip(0), chip(1, coords=[0.0, 0.0])]
    pos = js.call("topoLayout", collide)
    assert pos == [[0, 0], [1, 0]]
    # No coords at all -> grid.
    bare = [chip(i, coords=[]) for i in range(4)]
    assert js.call("topoLayout", bare) == [[0, 0], [1, 0], [2, 0], [0, 1]]


def test_topo_draw_full(js):
    ctx = RecordingCtx()
    chips = [chip(i) for i in range(8)]
    chips[3]["ici_link_up"] = False
    hits = js.call("topoDraw", ctx.js(), chips, 800.0, 260.0)
    assert len(hits) == 8
    assert hits[0]["chip"]["chip"] == "h/chip-0"
    # Every chip drew its index label; slice caption drawn once.
    texts = [op[1][0] for op in ctx.calls("fillText")]
    for i in range(8):
        assert str(i) in texts
    assert "slice-0 · 8 chips" in texts
    # The downed chip's ring strokes red at some point.
    strokes = {op[2]["strokeStyle"] for op in ctx.calls("stroke")}
    assert "#ef4444" in strokes
    # Mesh edges drawn (4x2 grid => 10 neighbor edges) + chip rings.
    assert len(ctx.calls("arc")) >= 16  # 8 rings + 8 HBM arcs


def test_topo_draw_multi_slice(js):
    ctx = RecordingCtx()
    chips = [chip(i) for i in range(4)] + [
        chip(i, slice_id="slice-1") for i in range(4)
    ]
    hits = js.call("topoDraw", ctx.js(), chips, 800.0, 260.0)
    assert len(hits) == 8
    texts = [op[1][0] for op in ctx.calls("fillText")]
    assert "slice-0 · 4 chips" in texts and "slice-1 · 4 chips" in texts


def test_pod_badge(js):
    assert js.call("podBadge", {"status": "Running"}) == {
        "cls": "badge Running", "text": "Running"}
    assert js.call("podBadge", {"status": "Failed", "reason": "OOMKilled"}) == {
        "cls": "badge Failed", "text": "Failed · OOMKilled"}
    # A reason on a Running pod (e.g. recovered) doesn't clutter the badge.
    assert js.call("podBadge", {"status": "Running", "reason": "x"})["text"] == "Running"
    assert js.call("podBadge", {}) == {"cls": "badge Unknown", "text": "?"}


def test_pod_tpu_cell(js):
    assert js.call("podTpuCell", {}) == "–"
    assert js.call("podTpuCell", {"tpu_request": 4.0}) == "4 req"
    assert js.call("podTpuCell", {"tpu_request": 4.0, "chips": 4.0}) == "4 req · 4 live"


def test_overall_dot_class(js):
    assert js.call("overallDotClass", {"critical": [1.0]}) == "bad"
    assert js.call("overallDotClass", {"serious": [1.0]}) == "warn"
    assert js.call("overallDotClass", {"minor": [1.0]}) == "warn"
    assert js.call("overallDotClass", {"minor": [], "critical": []}) == "ok"
    assert js.call("overallDotClass", None) == "ok"


def test_silence_prefix(js):
    # Severity leaf stripped -> prefix mutes the whole condition.
    assert js.call("silencePrefix", "host.cpu.critical") == "host.cpu."
    assert js.call("silencePrefix", "chip.h0/chip-1.hbm.serious") == "chip.h0/chip-1.hbm."
    # Keys without a severity leaf pass through unchanged.
    assert js.call("silencePrefix", "chip.h0/chip-1.ici_down") == "chip.h0/chip-1.ici_down"


def test_mean_of(js):
    assert js.call("meanOf", [1.0, None, 3.0]) == 2.0
    assert js.call("meanOf", [None, None]) is None
    assert js.call("meanOf", []) is None


# --------------------------------------------------------------- served

def test_chartcore_served_and_included():
    """The browser loads /chartcore.js before the inline script; the
    server must serve the same bytes this suite executed."""
    import asyncio

    from tests.test_server_api import serve

    with open(CHARTCORE) as f:
        src = f.read()
    sampler, server = serve()

    async def check():
        status, ctype, body = await server.handle("GET", "/chartcore.js")
        assert status == 200 and "javascript" in ctype
        assert body.decode() == src
        status, _, html = await server.handle("GET", "/")
        assert b'<script src="/chartcore.js"></script>' in html

    asyncio.run(check())


# ------------------------------------------------------------- artifact

def test_svg_artifact_renders(js, tmp_path):
    """The committed docs/dashboard.svg is produced by this exact path
    (tools/render_dashboard.py); prove it stays renderable."""
    ctx = RecordingCtx()
    labels = [f"10:{i:02d}" for i in range(16)]
    data = [[30 + 25 * ((i * 7) % 10) / 10 for i in range(16)],
            [55.0 + (i % 5) for i in range(16)]]
    js.call("chartDraw", ctx.js(), GEOM, labels, data, SERIES,
            {"yMax": 100.0, "unit": "%"})
    svg = ops_to_svg(ctx.ops, GEOM["w"], GEOM["h"])
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert "<path" in svg and "<text" in svg
    (tmp_path / "chart.svg").write_text(svg)
