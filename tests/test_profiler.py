"""Device trace capture (tpumon.profiler + /api/profile, SURVEY §5.1)."""

import asyncio
import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tests.test_server_api import get_json, run_app, serve
from tpumon.profiler import ProfileBusy, ProfilerService


def device_work(stop: threading.Event):
    x = jnp.ones((64, 64))
    while not stop.is_set():
        (x @ x).block_until_ready()


def test_capture_produces_xplane_dump(tmp_path):
    svc = ProfilerService(base_dir=str(tmp_path))
    stop = threading.Event()
    t = threading.Thread(target=device_work, args=(stop,), daemon=True)
    t.start()
    try:
        result = asyncio.run(svc.capture(seconds=0.3))
    finally:
        stop.set()
        t.join()
    assert result["total_bytes"] > 0
    assert any(f["file"].endswith(".xplane.pb") for f in result["files"])
    assert result["dir"].startswith(str(tmp_path))
    assert svc.status()["last"] == result
    assert svc.status()["busy"] is False


def test_capture_clamps_seconds(tmp_path):
    svc = ProfilerService(base_dir=str(tmp_path), max_seconds=0.2)
    result = asyncio.run(svc.capture(seconds=999))
    # The assertion proves the CLAMP (999 -> 0.2s), not the capture
    # overhead: "seconds" is wall time including jax trace start/stop
    # and serialization, which under full-suite load has been observed
    # past 2s (the flake CHANGES.md carried since PR 4). Any bound well
    # under the unclamped 999 proves clamping; 10s absorbs a loaded box.
    assert result["seconds"] < 10.0  # clamped to max_seconds, not 999


def test_single_capture_at_a_time(tmp_path):
    svc = ProfilerService(base_dir=str(tmp_path))

    async def two():
        first = asyncio.create_task(svc.capture(seconds=0.5))
        await asyncio.sleep(0.1)  # let the first actually start
        with pytest.raises(ProfileBusy):
            await svc.capture(seconds=0.1)
        return await first

    assert asyncio.run(two())["seconds"] >= 0.5


class TestProfileEndpoint:
    @pytest.fixture()
    def app(self):
        sampler, server = serve()
        loop = asyncio.new_event_loop()
        port = loop.run_until_complete(run_app(sampler, server))
        yield loop, port
        loop.run_until_complete(server.stop())
        loop.close()

    def _get_threaded(self, loop, port, path):
        """GET from a worker thread while the loop serves."""
        out = {}

        def fetch():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}"
                ) as r:
                    out["status"], out["body"] = r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                out["status"], out["body"] = e.code, json.loads(e.read())

        t = threading.Thread(target=fetch)
        t.start()
        while t.is_alive():
            loop.run_until_complete(asyncio.sleep(0.02))
        return out["status"], out["body"]

    def test_status_without_seconds(self, app):
        loop, port = app
        status, body = self._get_threaded(loop, port, "/api/profile")
        assert status == 200
        assert body["busy"] is False

    def test_capture_via_endpoint(self, app):
        loop, port = app
        status, body = self._get_threaded(
            loop, port, "/api/profile?seconds=0.2"
        )
        assert status == 200
        assert body["total_bytes"] > 0
        assert body["seconds"] >= 0.2

    def test_bad_seconds_is_400(self, app):
        loop, port = app
        status, body = self._get_threaded(
            loop, port, "/api/profile?seconds=nope"
        )
        assert status == 400
        assert "seconds" in body["error"]
