"""Training-loop checkpoint/resume (tpumon.loadgen.train).

Pins the elastic-recovery contract (SURVEY §5.3/§5.4): a killed run
resumed from its checkpoint produces the SAME final params as an
uninterrupted run — synthetic batches are deterministic per step, so
resume continues the exact data order.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import ServingEngine
from tpumon.loadgen.train import TrainConfig, run_train, synthetic_batch

MODEL = ModelConfig(
    vocab=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2, d_ff=64, max_seq=32
)


def cfg(**kw):
    base = dict(model=MODEL, steps=6, batch=4, seq=16, ckpt_every=3)
    base.update(kw)
    return TrainConfig(**base)


def max_param_diff(a, b) -> float:
    return max(
        jax.tree.leaves(
            jax.tree.map(lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)
        )
    )


def test_synthetic_batches_deterministic():
    c = cfg()
    assert jnp.array_equal(synthetic_batch(c, 3), synthetic_batch(c, 3))
    assert not jnp.array_equal(synthetic_batch(c, 3), synthetic_batch(c, 4))


def test_resume_matches_uninterrupted_run(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "model"))

    full = run_train(cfg(), mesh=mesh)  # no checkpointing: ground truth

    d = str(tmp_path)
    first = run_train(cfg(steps=3, ckpt_dir=d), mesh=mesh)  # "killed" at 3
    assert first["resumed_from"] is None
    second = run_train(cfg(ckpt_dir=d), mesh=mesh)  # same command, rerun
    assert second["resumed_from"] == 3
    assert second["step"] == 5
    assert max_param_diff(full["params"], second["params"]) < 1e-5
    assert abs(full["loss"] - second["loss"]) < 1e-5


def test_completed_run_resumes_to_noop(tmp_path):
    d = str(tmp_path)
    done = run_train(cfg(ckpt_dir=d))
    again = run_train(cfg(ckpt_dir=d))
    assert again["resumed_from"] == 6  # past the last step: loop body skipped
    assert again["loss"] is None  # no steps ran; no fake/NaN loss reported
    assert max_param_diff(done["params"], again["params"]) == 0.0


def test_single_device_path(tmp_path, monkeypatch):
    import tpumon.loadgen.train as train_mod

    monkeypatch.setattr(train_mod, "_default_mesh", lambda: None)
    d = str(tmp_path)
    out = train_mod.run_train(cfg(steps=2, ckpt_dir=d, ckpt_every=1))
    assert np.isfinite(out["loss"])
    again = train_mod.run_train(cfg(steps=2, ckpt_dir=d, ckpt_every=1))
    assert again["resumed_from"] == 2


def test_serving_engine_serves_trained_checkpoint(tmp_path):
    d = str(tmp_path)
    trained = run_train(cfg(ckpt_dir=d))

    from tpumon.loadgen.serving import ServeConfig

    engine = ServingEngine(
        cfg=ServeConfig(model=MODEL, slots=2, prefill_len=8), ckpt_dir=d
    )
    assert engine.ckpt_step == 5
    host_params = jax.device_get(trained["params"])
    assert max_param_diff(host_params, jax.device_get(engine.params)) < 1e-6
    r = engine.submit([1, 2, 3], max_new=2)
    while not r.done.is_set():
        engine.step()
    assert len(r.output) >= 2  # prefill's first token + decode steps


def test_serving_engine_ignores_mismatched_checkpoint(tmp_path):
    d = str(tmp_path)
    run_train(cfg(steps=2, ckpt_dir=d))

    from tpumon.loadgen.serving import ServeConfig

    other = ModelConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
        max_seq=32,
    )
    engine = ServingEngine(
        cfg=ServeConfig(model=other, slots=2, prefill_len=8), ckpt_dir=d
    )
    assert engine.ckpt_step is None  # cold init, no crash


def test_serving_engine_adopts_checkpoint_config(tmp_path):
    """The --loadgen-ckpt CLI path: no explicit ServeConfig, so the engine
    must take the architecture from the checkpoint's meta — otherwise the
    default config can never match and trained weights silently never
    load."""
    d = str(tmp_path)
    run_train(cfg(steps=2, ckpt_dir=d))
    engine = ServingEngine(ckpt_dir=d)
    assert engine.cfg.model == MODEL
    assert engine.ckpt_step == 1


def test_run_train_sp_mode():
    """--parallel sp: the trainer runs the sequence-parallel step over
    the full virtual mesh (zigzag schedule) and the loss is finite."""
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig, run_train

    import jax
    import pytest as _pytest

    n = len(jax.devices())
    if n < 2:
        _pytest.skip("sp mode refuses single-device (by design)")
    seq = 2 * n * 2 + 1  # seq-1 divisible by 2n
    cfg = TrainConfig(
        model=ModelConfig(vocab=128, d_model=32, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=64, max_seq=seq),
        steps=2, batch=2, seq=seq, parallel="sp")
    out = run_train(cfg)
    assert out["step"] == 1 and out["loss"] is not None
    import numpy as np

    assert np.isfinite(out["loss"])


def test_train_config_rejects_unknown_parallel():
    import pytest as _pytest

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig

    with _pytest.raises(ValueError, match="parallel"):
        TrainConfig(model=ModelConfig(), parallel="pp")


def test_run_train_sp_rejects_single_device_and_indivisible_seq():
    import jax
    import pytest as _pytest

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig, run_train

    if len(jax.devices()) < 2:
        # The refusal IS the contract on a 1-device host: sp must
        # never silently fall back to the dense step.
        with _pytest.raises(ValueError, match="device"):
            run_train(TrainConfig(model=ModelConfig(), steps=1,
                                  parallel="sp"))
        return
    cfg = TrainConfig(
        model=ModelConfig(vocab=128, d_model=32, n_layers=1, n_heads=4,
                          n_kv_heads=2, d_ff=64, max_seq=64),
        steps=1, batch=1, seq=30, parallel="sp")
    with _pytest.raises(ValueError, match="divisible"):
        run_train(cfg)


def test_train_induction_learns_copying():
    """train_induction (Adam, fused scan) must actually learn the
    periodic-continuation task — the honesty precondition for the
    prompt-lookup speculation bench (plain SGD at default lr does
    not; see the function docstring)."""
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import train_induction

    m = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=128,
                    compute_dtype="float32")
    params, losses = train_induction(m, steps=700, period=8, seq=64,
                                     batch=8)
    first, last = float(losses[0]), float(losses[-1])
    # Irreducible floor: the first period is unpredictable
    # (8/64 * ln(63) ~ 0.52); 700 Adam steps land near it (measured
    # ~0.64 on the CPU test shape).
    assert last < 1.0, (first, last)
    assert jax.tree.all(jax.tree.map(
        lambda x: bool(jnp.all(jnp.isfinite(x))), params))
