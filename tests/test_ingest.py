"""Ingest spine (docs/perf.md): batch append into the columnar TSDB,
native kernel vs pure-Python parity, and the sampler's batched per-chip
recording.

The load-bearing guarantee: the C kernel (tpumon/native/tsdbkern.cpp)
and the pure-Python fallback produce BIT-EXACT state — same head column
bytes, same sealed chunk bytes, same downsample accumulators — so a
deployment without the .so differs only in speed. The golden test
drives both over the checked-in fuzz corpus (tests/fixtures/
tsdb_fuzz.json) and compares raw bytes.
"""

import asyncio
import json
import os
import shutil

import pytest

from tpumon import native, tsdb
from tpumon.history import RingHistory, RingSeries

FUZZ = os.path.join(os.path.dirname(__file__), "fixtures", "tsdb_fuzz.json")

kernel_available = pytest.mark.skipif(
    shutil.which("g++") is None and native.load_tsdb(auto_build=False) is None,
    reason="no g++ and no prebuilt tsdb kernel",
)


@pytest.fixture
def force_python():
    tsdb.set_kernel_enabled(False)
    yield
    tsdb.set_kernel_enabled(True)


def series_state(s: RingSeries) -> tuple:
    """Everything observable about a series' storage, as raw bytes
    (bsum packed so a NaN accumulator compares bit-wise, not by the
    NaN != NaN rule)."""
    import struct

    def tier_state(t: tsdb.Tier) -> tuple:
        return (
            t.head_ts.tobytes(),
            t.head_val.tobytes(),
            tuple((c.start_ms, c.end_ms, c.count, c.data) for c in t.chunks),
        )

    return (
        tier_state(s.fine),
        tuple(
            (tier_state(d.tier), d.bucket, struct.pack("<d", d.bsum), d.bn)
            for d in s.down
        ),
    )


def make_series() -> RingSeries:
    # Small seal size so the corpus crosses many chunk boundaries; both
    # downsample tiers active.
    s = RingSeries(
        window_s=3600, long_window_s=24 * 3600, coarse_step_s=60.0,
        mid_step_s=30.0, mid_window_s=6 * 3600,
    )
    s.fine.seal_points = 64
    return s


def corpus():
    with open(FUZZ) as f:
        data = json.load(f)
    for entry in data:
        ts = [t / 1000.0 for t in entry["ts_ms"]]
        # nan/inf ride as strings in the JSON corpus.
        yield entry["name"], ts, [float(v) for v in entry["values"]]


@kernel_available
def test_kernel_python_parity_golden():
    """C kernel and Python fallback land bit-identical state over the
    fuzz corpus, fed in mixed batch sizes (1, 7, 64, 200)."""
    assert native.load_tsdb(auto_build=True) is not None
    sizes = [1, 7, 64, 200]
    for name, ts, vals in corpus():
        tsdb.set_kernel_enabled(True)
        assert tsdb.kernel() is not None, "kernel failed to load"
        a = make_series()
        i = k = 0
        while i < len(ts):
            n = sizes[k % len(sizes)]
            k += 1
            a.add_batch(ts[i : i + n], vals[i : i + n])
            i += n
        tsdb.set_kernel_enabled(False)
        try:
            b = make_series()
            i = k = 0
            while i < len(ts):
                n = sizes[k % len(sizes)]
                k += 1
                b.add_batch(ts[i : i + n], vals[i : i + n])
                i += n
        finally:
            tsdb.set_kernel_enabled(True)
        assert series_state(a) == series_state(b), f"divergence in {name!r}"


def test_batch_matches_per_point(force_python):
    """One big add_batch == the same stream through add(), bit-exact:
    same chunk boundaries (seals trigger at identical counts), same
    accumulators. Pure-Python both sides; the golden test above pins
    C==Python, so transitivity covers C==per-point."""
    for name, ts, vals in corpus():
        a = make_series()
        a.add_batch(ts, vals)
        b = make_series()
        for t, v in zip(ts, vals):
            b.add(t, v)
        assert series_state(a) == series_state(b), f"divergence in {name!r}"


def test_batch_matches_per_point_with_kernel():
    """Same equivalence on whatever path this environment actually runs
    (kernel if built): the contract is path-independent."""
    name, ts, vals = next(corpus())
    a = make_series()
    a.add_batch(ts, vals)
    b = make_series()
    for t, v in zip(ts, vals):
        b.add(t, v)
    assert series_state(a) == series_state(b)


def test_out_of_order_batch_falls_back_sorted():
    """A batch with a backwards timestamp takes the per-point path:
    add_batch returns False, data still lands sorted, and the tier's
    out_of_order counter records the slow-path hits."""
    s = make_series()
    ts = [1000.0, 1001.0, 1000.5, 1002.0]
    assert s.add_batch(ts, [1.0, 2.0, 3.0, 4.0]) is False
    pts = s.fine.since(None)
    assert [t for t, _ in pts] == sorted(t for t in ts)
    assert s.fine.out_of_order == 1

    ring = RingHistory()
    ring.record_series("x", ts, [1.0, 2.0, 3.0, 4.0])
    assert ring.out_of_order == 1
    # record() counts too
    ring.record("x", 9.0, ts=999.0)
    assert ring.out_of_order == 2


def test_record_batch_multi_series_and_mutations():
    """record_batch: one point lands per series (None skipped), the
    ring's mutation counter bumps ONCE per batch (the snapshotter's
    dirty-skip sees ticks, not series), and each touched series'
    version moves so the resample memo invalidates."""
    ring = RingHistory()
    h_a = ring.handle("a")
    h_b = ring.handle("b")
    m0 = ring.mutations
    ring.record_batch([(h_a, 1.0), (h_b, 2.0), ("c", 3.0), ("d", None)], ts=1000.0)
    assert ring.mutations == m0 + 1
    assert set(ring.series) == {"a", "b", "c"}  # None never creates "d"
    assert ring.handle("a") is h_a  # stable handle
    assert [v for _, v in h_a.fine.since(None)] == [1.0]
    assert [v for _, v in ring.series["c"].fine.since(None)] == [3.0]

    # Memo correctness: a cached render must invalidate when the batch
    # path appends (versions bump per touched series per batch).
    ring.record_batch([(h_a, 5.0)], ts=1030.0)
    out1 = ring.snapshot_series("a", step_s=30.0)
    assert ring.snapshot_series("a", step_s=30.0) is out1  # memo hit
    ring.record_batch([(h_a, 7.0)], ts=1060.0)
    out2 = ring.snapshot_series("a", step_s=30.0)
    assert out2 is not out1 and out2["data"][-1] == 7.0

    # An all-None batch records nothing and stays clean for dirty-skip.
    m1 = ring.mutations
    ring.record_batch([(h_a, None), ("zz", None)], ts=1090.0)
    assert ring.mutations == m1 and "zz" not in ring.series


def test_record_batch_matches_record(force_python):
    """The batched sampler shape (many series, one shared ts per tick)
    lands the same state as per-point record() calls."""
    names = [f"chip.c{i}.mxu" for i in range(17)] + ["cpu", "mxu"]
    a, b = RingHistory(), RingHistory()
    for tick in range(200):
        ts = 1_700_000_000.0 + tick
        pairs = [(n, (i * 7 + tick) % 100 + 0.25) for i, n in enumerate(names)]
        a.record_batch(pairs, ts=ts)
        for n, v in pairs:
            b.record(n, v, ts=ts)
    for n in names:
        sa, sb = a.series[n], b.series[n]
        assert sa.fine.since(None) == sb.fine.since(None), n
        for da, db in zip(sa.down, sb.down):
            assert (da.bucket, da.bsum, da.bn) == (db.bucket, db.bsum, db.bn)
            assert da.tier.since(None) == db.tier.since(None), n


@kernel_available
def test_record_batch_kernel_matches_python():
    """accum_many (the one-call-per-tick downsample path) is bit-exact
    across kernel and fallback, including bucket flushes for series
    that skip ticks. The batch must clear ACCUM_KERNEL_MIN or the
    size heuristic would route both runs through the fallback and the
    kernel path would go untested."""
    def run() -> RingHistory:
        ring = RingHistory()
        # 1/5 of the series skip each tick, so the live batch is
        # ~48*4/5 = 38 series — comfortably above the heuristic.
        assert tsdb.ACCUM_KERNEL_MIN <= 38
        names = [f"s{i}" for i in range(48)]
        for tick in range(150):
            ts = 1_700_000_000.0 + tick
            pairs = [
                (n, None if (tick + i) % 5 == 0 else float(i) + tick * 0.01)
                for i, n in enumerate(names)
            ]
            ring.record_batch(pairs, ts=ts)
        return ring

    tsdb.set_kernel_enabled(True)
    assert tsdb.kernel() is not None
    a = run()
    tsdb.set_kernel_enabled(False)
    try:
        b = run()
    finally:
        tsdb.set_kernel_enabled(True)
    for n in a.series:
        assert series_state(a.series[n]) == series_state(b.series[n]), n


def test_snapshot_roundtrip_after_batch(tmp_path):
    """Binary history snapshots round-trip batch-written state,
    including the slot-backed downsample accumulators."""
    from tpumon.history import HistorySnapshotter

    import time as _time

    ring = RingHistory()
    base = _time.time() - 700  # recent: restore retention must keep it
    for tick in range(700):
        ring.record_batch(
            [("cpu", 50.0 + tick % 13), ("mxu", 70.0)], ts=base + tick
        )
    path = str(tmp_path / "hist.bin")
    assert HistorySnapshotter(ring, path).save()
    fresh = RingHistory()
    assert HistorySnapshotter(fresh, path).restore()
    for n in ("cpu", "mxu"):
        assert fresh.series[n].fine.since(None) == ring.series[n].fine.since(None)
        for da, db in zip(fresh.series[n].down, ring.series[n].down):
            assert (da.bucket, da.bsum, da.bn) == (db.bucket, db.bsum, db.bn)
    # Restore bumped the generation: stale handles must be re-resolved.
    assert fresh.generation > 0


def test_sampler_perchip_handles_cached_and_health():
    """The sampler resolves per-chip series once (cached name tuples +
    handles), reuses them every tick, and surfaces ingest-spine health
    (kernel flag + out-of-order count)."""
    from tpumon.config import load_config
    from tpumon.sampler import Sampler
    from tpumon.collectors.accel_fake import FakeTpuCollector

    cfg = load_config(env={"TPUMON_COLLECTORS": "accel", "TPUMON_HISTORY_PER_CHIP": "8"})
    sampler = Sampler(cfg, accel=FakeTpuCollector(topology="v5e-4"))

    async def scenario():
        await sampler.tick_fast()
        entry = sampler._perchip_handles[sampler.chips()[0].chip_id]
        handle0 = entry[1][0]
        assert handle0 is not None
        await sampler.tick_fast()
        assert sampler._perchip_handles[sampler.chips()[0].chip_id][1][0] is handle0
        h = sampler.health_json()["history"]
        assert h["out_of_order_appends"] == 0
        assert isinstance(h["ingest_kernel"], bool)
        assert h["per_chip_tracked"] == 4

    asyncio.run(scenario())


def test_sampler_out_of_order_journals_once():
    """A backwards clock produces ONE 'history' journal event (plus the
    running counter) — not one per tick."""
    from tpumon.config import load_config
    from tpumon.sampler import Sampler
    from tpumon.collectors.accel_fake import FakeTpuCollector

    cfg = load_config(env={"TPUMON_COLLECTORS": "accel"})
    sampler = Sampler(cfg, accel=FakeTpuCollector(topology="v5e-4"))

    async def scenario():
        await sampler.tick_fast()  # baseline established, clean
        t0 = 2_000_000_000.0
        sampler._record_history(t0)
        sampler._record_history(t0 - 60.0)  # clock jumped backwards
        sampler._record_history(t0 - 120.0)
        assert sampler.history.out_of_order > 0
        events = [
            e for e in sampler.journal.after(0, kind="history")
            if "out-of-order" in e["msg"]
        ]
        assert len(events) == 1

    asyncio.run(scenario())


def test_load_points_replays_through_batch(force_python):
    """v1-style point dumps restore through the batch path and match a
    per-point replay (the seam-bucket rule still holds)."""
    src = RingHistory()
    for tick in range(500):
        src.record("cpu", 40.0 + tick % 7, ts=1_700_000_000.0 + tick)
    now = 1_700_000_000.0 + 500
    dumped = src.dump_points()
    coarse = src.dump_coarse()
    a, b = RingHistory(), RingHistory()
    a.load_points(dumped, coarse, now=now)
    # Reference: the old per-point restore semantics.
    for name, pts in coarse.items():
        bound = min(t for t, _ in dumped[name]) if dumped.get(name) else None
        bstart = None if bound is None else (bound // 60.0) * 60.0
        b.restore_coarse(name, [p for p in pts if bstart is None or p[0] < bstart])
    for name, pts in dumped.items():
        for t, v in pts:
            b.record(name, v, ts=t)
    assert a.series["cpu"].fine.since(None) == b.series["cpu"].fine.since(None)
    assert (
        a.series["cpu"]._coarse.tier.since(None)
        == b.series["cpu"]._coarse.tier.since(None)
    )
    assert a.generation == 1


def test_evict_pacing_keeps_retention_for_windowed_reads():
    """The batch path's paced eviction never leaks expired points into
    windowed reads (readers pass explicit starts), and resident overhang
    stays bounded near window/16."""
    s = RingSeries(window_s=100.0)
    base = 1000.0
    ring = RingHistory(window_s=100.0, long_window_s=100.0, mid_step_s=0)
    h = ring.handle("x")
    for tick in range(400):
        ring.record_batch([(h, float(tick))], ts=base + tick)
    pts = h.fine.since(base + 400 - 100.0)
    assert pts[0][0] >= base + 300 and pts[-1][1] == 399.0
    # Resident data is bounded: window + seal/pacing slack, not 400s.
    resident = h.fine.dump()
    assert resident[0][0] >= base + 400 - 100.0 - 32.0


@kernel_available
def test_native_build_covers_tsdb_kernel():
    """python -m tpumon.native build compiles BOTH shared libraries and
    the kernel passes its ABI gate."""
    assert native.build()
    assert os.path.exists(native.TSDB_SO_PATH)
    assert native.load_tsdb(auto_build=False) is not None
