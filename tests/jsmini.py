"""jsmini — a small ES-subset interpreter, test infrastructure only.

This environment has no JavaScript engine (no node/quickjs/browser —
verified in the round-2 probe), yet VERDICT round-1 weak #3 rightly
demands the dashboard's frontend logic be *executed* by a test, not
regex-matched. jsmini closes that gap: the dashboard's pure logic lives
in ``tpumon/web/chartcore.js`` written in a deliberately restricted
dialect, and tests run that actual shipped file here.

Supported dialect (chartcore.js is reviewed against this list; anything
outside it raises SyntaxError at parse time so the dialect cannot widen
silently):

- ``function`` declarations, arrow functions (expr + block bodies),
  closures
- const/let/var (with flat array-destructuring declarations),
  assignment ops ``= += -= *= /=``, postfix/prefix ``++ --``
- if/else, while, C-style for, for..of, return/break/continue
- numbers, strings, template literals, array/object literals,
  true/false/null/undefined, Infinity, NaN
- ``+ - * / % **``, comparisons (``=== !== == != < <= > >=``),
  ``&& || !``, ternary, ``??``, grouping; JS ``+`` string-concat
  semantics with JS number formatting
- member access ``a.b`` / ``a[i]``, calls, spread in call args
  (``Math.max(...xs)``)
- method tables for arrays (push/map/filter/forEach/join/slice/concat/
  indexOf/includes/reduce/sort/some/every/fill/find), strings
  (slice/split/padStart/repeat/includes/toUpperCase/toLowerCase/
  charCodeAt/trim), numbers (toFixed), ``Math.*``, ``JSON.stringify``,
  ``Object.keys/values``, ``Array.isArray``, isFinite, parseFloat,
  parseInt, Number, String

Deliberately ABSENT (keep the chart core free of them): classes/this/
new, async, try/catch, regex, getters, prototypes, labels, switch.

JS runtime errors (property access on undefined, calling a non-
function) raise JsError — i.e. a TypeError thrown by the chart code
fails the test, which is the whole point.
"""

from __future__ import annotations

import inspect
import math
import re
from dataclasses import dataclass
from typing import Any, Callable


class JsError(Exception):
    """Runtime error inside interpreted JS (TypeError/RangeError…)."""


class JsSyntaxError(Exception):
    """chartcore.js stepped outside the supported dialect."""


class _Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEF = _Undefined()


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_PUNCT = [
    "=>", "===", "!==", "==", "!=", "<=", ">=", "&&", "||", "??", "?.",
    "++", "--", "+=", "-=", "*=", "/=", "%=", "**", "...",
    "{", "}", "(", ")", "[", "]", ";", ",", ".", "?", ":",
    "=", "+", "-", "*", "/", "%", "<", ">", "!",
]
_KEYWORDS = {
    "function", "return", "if", "else", "for", "while", "of", "const",
    "let", "var", "true", "false", "null", "undefined", "break",
    "continue", "typeof", "in",
}
# Constructs outside the supported dialect fail loudly at parse time
# (otherwise `class X {}` would lex as innocent identifiers).
_RESERVED = {
    "class", "new", "this", "async", "await", "try", "catch", "finally",
    "throw", "switch", "case", "default", "delete", "instanceof",
    "extends", "super", "yield", "static", "do", "with", "void",
    "import", "export",
}
_NUM_RE = re.compile(r"0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+")
_ID_RE = re.compile(r"[A-Za-z_$][A-Za-z0-9_$]*")


@dataclass
class Tok:
    kind: str  # num str tpl id kw punct eof
    val: Any
    pos: int
    line: int


def tokenize(src: str) -> list[Tok]:
    toks: list[Tok] = []
    i, n, line = 0, len(src), 1
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise JsSyntaxError(f"unterminated comment at line {line}")
            line += src.count("\n", i, j)
            i = j + 2
            continue
        if c in "'\"":
            j, buf = i + 1, []
            while j < n and src[j] != c:
                if src[j] == "\\":
                    buf.append(_escape(src[j + 1]))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JsSyntaxError(f"unterminated string at line {line}")
            toks.append(Tok("str", "".join(buf), i, line))
            i = j + 1
            continue
        if c == "`":
            parts: list[tuple[str, Any]] = []  # ("str", s) | ("expr", toks)
            j, buf = i + 1, []
            while j < n and src[j] != "`":
                if src[j] == "\\":
                    buf.append(_escape(src[j + 1]))
                    j += 2
                elif src.startswith("${", j):
                    parts.append(("str", "".join(buf)))
                    buf = []
                    depth, k = 1, j + 2
                    while k < n and depth:
                        if src[k] == "{":
                            depth += 1
                        elif src[k] == "}":
                            depth -= 1
                        k += 1
                    if depth:
                        raise JsSyntaxError(f"unterminated ${{ at line {line}")
                    parts.append(("expr", src[j + 2:k - 1]))
                    j = k
                else:
                    if src[j] == "\n":
                        line += 1
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise JsSyntaxError(f"unterminated template at line {line}")
            parts.append(("str", "".join(buf)))
            toks.append(Tok("tpl", parts, i, line))
            i = j + 1
            continue
        m = _NUM_RE.match(src, i)
        if m and (c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit())):
            text = m.group(0)
            toks.append(
                Tok(
                    "num",
                    int(text, 16) if text[:2].lower() == "0x" else float(text),
                    i,
                    line,
                )
            )
            i = m.end()
            continue
        m = _ID_RE.match(src, i)
        if m:
            name = m.group(0)
            if name in _RESERVED:
                raise JsSyntaxError(
                    f"line {line}: {name!r} is outside the jsmini dialect "
                    "(see tests/jsmini.py module docstring)"
                )
            toks.append(Tok("kw" if name in _KEYWORDS else "id", name, i, line))
            i = m.end()
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(Tok("punct", p, i, line))
                i += len(p)
                break
        else:
            raise JsSyntaxError(f"unexpected char {c!r} at line {line}")
    toks.append(Tok("eof", None, n, line))
    return toks


def _escape(c: str) -> str:
    return {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", "'": "'",
            '"': '"', "`": "`", "0": "\0", "$": "$"}.get(c, c)


# ---------------------------------------------------------------------------
# Parser -> tuple-based AST
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, toks: list[Tok]):
        self.toks = toks
        self.i = 0

    def peek(self, k: int = 0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, kind: str, val: Any = None) -> bool:
        t = self.peek()
        return t.kind == kind and (val is None or t.val == val)

    def eat(self, kind: str, val: Any = None) -> Tok:
        if not self.at(kind, val):
            t = self.peek()
            raise JsSyntaxError(
                f"line {t.line}: expected {val or kind}, got {t.kind} {t.val!r}"
            )
        return self.next()

    def opt(self, kind: str, val: Any = None) -> bool:
        if self.at(kind, val):
            self.next()
            return True
        return False

    # ---- statements ----

    def parse_program(self) -> list:
        body = []
        while not self.at("eof"):
            body.append(self.statement())
        return body

    def statement(self):
        if self.at("punct", "{"):
            return self.block()
        if self.at("kw", "function"):
            self.next()
            name = self.eat("id").val
            params = self.params()
            body = self.block()
            return ("fundecl", name, params, body)
        if self.peek().kind == "kw" and self.peek().val in ("const", "let", "var"):
            d = self.vardecl()
            self.opt("punct", ";")
            return d
        if self.opt("kw", "return"):
            if self.at("punct", ";") or self.at("punct", "}"):
                self.opt("punct", ";")
                return ("return", None)
            e = self.expression()
            self.opt("punct", ";")
            return ("return", e)
        if self.opt("kw", "if"):
            self.eat("punct", "(")
            cond = self.expression()
            self.eat("punct", ")")
            then = self.statement()
            other = None
            if self.opt("kw", "else"):
                other = self.statement()
            return ("if", cond, then, other)
        if self.opt("kw", "while"):
            self.eat("punct", "(")
            cond = self.expression()
            self.eat("punct", ")")
            return ("while", cond, self.statement())
        if self.opt("kw", "for"):
            return self.for_stmt()
        if self.opt("kw", "break"):
            self.opt("punct", ";")
            return ("break",)
        if self.opt("kw", "continue"):
            self.opt("punct", ";")
            return ("continue",)
        if self.opt("punct", ";"):
            return ("empty",)
        e = self.expression()
        self.opt("punct", ";")
        return ("expr", e)

    def block(self):
        self.eat("punct", "{")
        body = []
        while not self.at("punct", "}"):
            body.append(self.statement())
        self.eat("punct", "}")
        return ("block", body)

    def vardecl(self):
        kind = self.next().val  # const/let/var
        decls = []
        while True:
            if self.at("punct", "["):  # flat array destructuring
                self.next()
                names = []
                while not self.at("punct", "]"):
                    names.append(self.eat("id").val)
                    if not self.opt("punct", ","):
                        break
                self.eat("punct", "]")
                self.eat("punct", "=")
                decls.append(("arr", names, self.assignment()))
            else:
                name = self.eat("id").val
                init = None
                if self.opt("punct", "="):
                    init = self.assignment()
                decls.append(("one", name, init))
            if not self.opt("punct", ","):
                break
        return ("vardecl", kind, decls)

    def for_stmt(self):
        self.eat("punct", "(")
        # for (const x of expr)
        if (
            self.peek().kind == "kw"
            and self.peek().val in ("const", "let", "var")
            and self.peek(2).kind == "kw"
            and self.peek(2).val == "of"
        ):
            self.next()
            name = self.eat("id").val
            self.eat("kw", "of")
            it = self.expression()
            self.eat("punct", ")")
            return ("forof", name, it, self.statement())
        init = None
        if not self.at("punct", ";"):
            if self.peek().kind == "kw" and self.peek().val in ("const", "let", "var"):
                init = self.vardecl()
            else:
                init = ("expr", self.expression())
        self.eat("punct", ";")
        cond = None if self.at("punct", ";") else self.expression()
        self.eat("punct", ";")
        update = None if self.at("punct", ")") else self.expression()
        self.eat("punct", ")")
        return ("for", init, cond, update, self.statement())

    def params(self) -> list[str]:
        self.eat("punct", "(")
        out = []
        while not self.at("punct", ")"):
            out.append(self.eat("id").val)
            if not self.opt("punct", ","):
                break
        self.eat("punct", ")")
        return out

    # ---- expressions (precedence climbing) ----

    def expression(self):
        e = self.assignment()
        while self.opt("punct", ","):
            e = ("comma", e, self.assignment())
        return e

    def assignment(self):
        # Arrow function lookahead: ID => ...  or  ( params ) => ...
        if self.at("id") and self.peek(1).kind == "punct" and self.peek(1).val == "=>":
            name = self.next().val
            self.next()
            return self.arrow_body([name])
        if self.at("punct", "("):
            save = self.i
            try:
                params = self.params()
                if self.at("punct", "=>"):
                    self.next()
                    return self.arrow_body(params)
            except JsSyntaxError:
                pass
            self.i = save
        left = self.ternary()
        t = self.peek()
        if t.kind == "punct" and t.val in ("=", "+=", "-=", "*=", "/=", "%="):
            op = self.next().val
            right = self.assignment()
            if left[0] not in ("name", "member", "index"):
                raise JsSyntaxError(f"line {t.line}: bad assignment target")
            return ("assign", op, left, right)
        return left

    def arrow_body(self, params: list[str]):
        if self.at("punct", "{"):
            return ("arrow", params, self.block())
        return ("arrow", params, ("return", self.assignment()))

    def ternary(self):
        cond = self.nullish()
        if self.opt("punct", "?"):
            a = self.assignment()
            self.eat("punct", ":")
            b = self.assignment()
            return ("cond", cond, a, b)
        return cond

    def _binop(self, sub: Callable, ops: tuple[str, ...], node: str = "bin"):
        e = sub()
        while self.peek().kind == "punct" and self.peek().val in ops:
            op = self.next().val
            e = (node, op, e, sub())
        return e

    def nullish(self):
        return self._binop(self.logical_or, ("??",), "logic")

    def logical_or(self):
        return self._binop(self.logical_and, ("||",), "logic")

    def logical_and(self):
        return self._binop(self.equality, ("&&",), "logic")

    def equality(self):
        return self._binop(self.relational, ("===", "!==", "==", "!="))

    def relational(self):
        return self._binop(self.additive, ("<", "<=", ">", ">="))

    def additive(self):
        return self._binop(self.multiplicative, ("+", "-"))

    def multiplicative(self):
        return self._binop(self.exponent, ("*", "/", "%"))

    def exponent(self):
        e = self.unary()
        if self.at("punct", "**"):  # right-assoc
            self.next()
            return ("bin", "**", e, self.exponent())
        return e

    def unary(self):
        t = self.peek()
        if t.kind == "punct" and t.val in ("!", "-", "+"):
            self.next()
            return ("unary", t.val, self.unary())
        if t.kind == "punct" and t.val in ("++", "--"):
            self.next()
            return ("preincr", t.val, self.unary())
        if t.kind == "kw" and t.val == "typeof":
            self.next()
            return ("typeof", self.unary())
        return self.postfix()

    def postfix(self):
        e = self.call_member()
        t = self.peek()
        if t.kind == "punct" and t.val in ("++", "--"):
            self.next()
            return ("postincr", t.val, e)
        return e

    def call_member(self):
        e = self.primary()
        while True:
            if self.opt("punct", "."):
                e = ("member", e, self.eat_prop(), False)
            elif self.opt("punct", "?."):
                if self.at("punct", "["):  # a?.[i]
                    self.next()
                    idx = self.expression()
                    self.eat("punct", "]")
                    e = ("optindex", e, idx)
                else:
                    e = ("member", e, self.eat_prop(), True)
            elif self.at("punct", "["):
                self.next()
                idx = self.expression()
                self.eat("punct", "]")
                e = ("index", e, idx)
            elif self.at("punct", "("):
                e = ("call", e, self.args())
            else:
                return e

    def eat_prop(self) -> str:
        t = self.peek()
        if t.kind in ("id", "kw"):
            self.next()
            return t.val
        raise JsSyntaxError(f"line {t.line}: expected property name")

    def args(self) -> list:
        self.eat("punct", "(")
        out = []
        while not self.at("punct", ")"):
            if self.opt("punct", "..."):
                out.append(("spread", self.assignment()))
            else:
                out.append(self.assignment())
            if not self.opt("punct", ","):
                break
        self.eat("punct", ")")
        return out

    def primary(self):
        t = self.next()
        if t.kind == "num":
            return ("num", float(t.val))
        if t.kind == "str":
            return ("str", t.val)
        if t.kind == "tpl":
            parts = []
            for kind, payload in t.val:
                if kind == "str":
                    parts.append(("str", payload))
                else:
                    sub = Parser(tokenize(payload))
                    parts.append(("expr", sub.expression()))
                    sub.eat("eof")
            return ("tpl", parts)
        if t.kind == "id":
            return ("name", t.val)
        if t.kind == "kw":
            if t.val == "true":
                return ("bool", True)
            if t.val == "false":
                return ("bool", False)
            if t.val == "null":
                return ("null",)
            if t.val == "undefined":
                return ("undef",)
            if t.val == "function":  # anonymous function expression
                params = self.params()
                return ("arrow", params, self.block())
            raise JsSyntaxError(f"line {t.line}: unexpected keyword {t.val}")
        if t.kind == "punct":
            if t.val == "(":
                e = self.expression()
                self.eat("punct", ")")
                return e
            if t.val == "[":
                items = []
                while not self.at("punct", "]"):
                    if self.opt("punct", "..."):
                        items.append(("spread", self.assignment()))
                    else:
                        items.append(self.assignment())
                    if not self.opt("punct", ","):
                        break
                self.eat("punct", "]")
                return ("array", items)
            if t.val == "{":
                props = []
                while not self.at("punct", "}"):
                    k = self.peek()
                    if k.kind in ("id", "kw"):
                        self.next()
                        if self.opt("punct", ":"):
                            props.append((k.val, self.assignment()))
                        else:  # shorthand {x}
                            props.append((k.val, ("name", k.val)))
                    elif k.kind == "str":
                        self.next()
                        self.eat("punct", ":")
                        props.append((k.val, self.assignment()))
                    else:
                        raise JsSyntaxError(f"line {k.line}: bad object key")
                    if not self.opt("punct", ","):
                        break
                self.eat("punct", "}")
                return ("object", props)
        raise JsSyntaxError(f"line {t.line}: unexpected token {t.kind} {t.val!r}")


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class Env:
    def __init__(self, parent: "Env | None" = None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str):
        e = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        raise JsError(f"ReferenceError: {name} is not defined")

    def set(self, name: str, value):
        e = self
        while e is not None:
            if name in e.vars:
                e.vars[name] = value
                return
            e = e.parent
        raise JsError(f"ReferenceError: assignment to undeclared {name}")

    def declare(self, name: str, value):
        self.vars[name] = value


class JsFunction:
    def __init__(self, params: list[str], body, env: Env, interp: "Interp"):
        self.params = params
        self.body = body
        self.env = env
        self.interp = interp

    def __call__(self, *args):
        env = Env(self.env)
        for i, p in enumerate(self.params):
            env.declare(p, args[i] if i < len(args) else UNDEF)
        try:
            self.interp.exec_stmt(self.body, env)
        except _Return as r:
            return r.value
        return UNDEF


def js_num_str(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "Infinity"
    if v == -math.inf:
        return "-Infinity"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e21:
        return str(int(v))
    return repr(v)


def js_str(v) -> str:
    if v is UNDEF:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return js_num_str(v)
    if isinstance(v, str):
        return v
    if isinstance(v, list):
        return ",".join("" if x in (None, UNDEF) else js_str(x) for x in v)
    if isinstance(v, dict):
        return "[object Object]"
    return str(v)


def js_truthy(v) -> bool:
    if v is UNDEF or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return v != 0 and v == v
    if isinstance(v, str):
        return len(v) > 0
    return True


def js_num(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if v is None:
        return 0.0
    if v is UNDEF:
        return math.nan
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            return float(s)
        except ValueError:
            return math.nan
    return math.nan


def js_eq_loose(a, b) -> bool:
    """== — only the cases sane code relies on: null/undefined mutual
    equality, same-type compares, number<->string coercion."""
    if (a is None or a is UNDEF) or (b is None or b is UNDEF):
        return (a is None or a is UNDEF) and (b is None or b is UNDEF)
    if isinstance(a, str) and isinstance(b, float):
        return js_num(a) == b
    if isinstance(a, float) and isinstance(b, str):
        return a == js_num(b)
    return js_eq_strict(a, b)


def js_eq_strict(a, b) -> bool:
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (float, bool)) and isinstance(b, (float, bool)):
        return float(a) == float(b)
    if type(a) is not type(b):
        return False
    if isinstance(a, (list, dict)):
        return a is b
    return a == b


def _sort_key_default(x):
    return js_str(x)


class Interp:
    def __init__(self):
        self.global_env = Env()
        g = self.global_env
        g.declare("undefined", UNDEF)
        g.declare("Infinity", math.inf)
        g.declare("NaN", math.nan)
        g.declare("Math", {
            "max": lambda *a: max((js_num(x) for x in a), default=-math.inf),
            "min": lambda *a: min((js_num(x) for x in a), default=math.inf),
            "abs": lambda x: abs(js_num(x)),
            "floor": lambda x: float(math.floor(js_num(x))),
            "ceil": lambda x: float(math.ceil(js_num(x))),
            "round": lambda x: float(math.floor(js_num(x) + 0.5)),
            "sqrt": lambda x: math.sqrt(js_num(x)) if js_num(x) >= 0 else math.nan,
            "pow": lambda a, b: js_num(a) ** js_num(b),
            "sign": lambda x: float((js_num(x) > 0) - (js_num(x) < 0)),
            "trunc": lambda x: float(math.trunc(js_num(x))),
            "log": lambda x: math.log(js_num(x)) if js_num(x) > 0 else -math.inf,
            "log2": lambda x: math.log2(js_num(x)) if js_num(x) > 0 else -math.inf,
            "log10": lambda x: math.log10(js_num(x)) if js_num(x) > 0 else -math.inf,
            "sin": lambda x: math.sin(js_num(x)),
            "cos": lambda x: math.cos(js_num(x)),
            "hypot": lambda *a: math.hypot(*(js_num(x) for x in a)),
            "PI": math.pi,
            "E": math.e,
        })
        g.declare("JSON", {
            "stringify": lambda v, *a: _json_stringify(v),
        })
        g.declare("Object", {
            "keys": lambda o: list(o.keys()) if isinstance(o, dict) else [],
            "values": lambda o: list(o.values()) if isinstance(o, dict) else [],
            "entries": lambda o: [[k, v] for k, v in o.items()]
            if isinstance(o, dict) else [],
        })
        g.declare("Array", {"isArray": lambda v: isinstance(v, list)})
        g.declare("isFinite", lambda v: math.isfinite(js_num(v)))
        g.declare("isNaN", lambda v: js_num(v) != js_num(v))
        g.declare("parseFloat", _parse_float)
        g.declare("parseInt", lambda v, *a: _parse_int(v, *a))
        g.declare("Number", js_num)
        g.declare("String", js_str)
        g.declare("Boolean", js_truthy)
        g.declare("console", {"log": lambda *a: None, "error": lambda *a: None})

    # ---- public API ----

    def run(self, src: str, env: Env | None = None):
        env = env or self.global_env
        body = Parser(tokenize(src)).parse_program()
        # Hoist function declarations (mutual recursion).
        for stmt in body:
            if stmt[0] == "fundecl":
                env.declare(stmt[1], JsFunction(stmt[2], stmt[3], env, self))
        result = UNDEF
        for stmt in body:
            if stmt[0] == "fundecl":
                continue
            result = self.exec_stmt(stmt, env)
        return result

    def call(self, name: str, *args):
        fn = self.global_env.get(name)
        if not callable(fn):
            raise JsError(f"TypeError: {name} is not a function")
        return fn(*args)

    # ---- statements ----

    def exec_stmt(self, node, env: Env):
        op = node[0]
        if op == "block":
            block_env = Env(env)
            for stmt in node[1]:
                if stmt[0] == "fundecl":
                    block_env.declare(
                        stmt[1], JsFunction(stmt[2], stmt[3], block_env, self)
                    )
            for stmt in node[1]:
                if stmt[0] != "fundecl":
                    self.exec_stmt(stmt, block_env)
            return UNDEF
        if op == "expr":
            return self.eval(node[1], env)
        if op == "vardecl":
            for decl in node[2]:
                if decl[0] == "one":
                    _, name, init = decl
                    env.declare(
                        name, UNDEF if init is None else self.eval(init, env)
                    )
                else:
                    _, names, init = decl
                    val = self.eval(init, env)
                    if not isinstance(val, list):
                        raise JsError(
                            "TypeError: destructuring a non-array value"
                        )
                    for i, nm in enumerate(names):
                        env.declare(nm, val[i] if i < len(val) else UNDEF)
            return UNDEF
        if op == "fundecl":
            env.declare(node[1], JsFunction(node[2], node[3], env, self))
            return UNDEF
        if op == "return":
            raise _Return(UNDEF if node[1] is None else self.eval(node[1], env))
        if op == "if":
            if js_truthy(self.eval(node[1], env)):
                self.exec_stmt(node[2], env)
            elif node[3] is not None:
                self.exec_stmt(node[3], env)
            return UNDEF
        if op == "while":
            while js_truthy(self.eval(node[1], env)):
                try:
                    self.exec_stmt(node[2], env)
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEF
        if op == "for":
            _, init, cond, update, body = node
            loop_env = Env(env)
            if init is not None:
                self.exec_stmt(init, loop_env)
            while cond is None or js_truthy(self.eval(cond, loop_env)):
                try:
                    self.exec_stmt(body, loop_env)
                except _Break:
                    break
                except _Continue:
                    pass
                if update is not None:
                    self.eval(update, loop_env)
            return UNDEF
        if op == "forof":
            _, name, it_expr, body = node
            it = self.eval(it_expr, env)
            if isinstance(it, dict):
                raise JsError("TypeError: object is not iterable")
            if it is UNDEF or it is None:
                raise JsError("TypeError: undefined is not iterable")
            for item in list(it):
                loop_env = Env(env)
                loop_env.declare(name, item)
                try:
                    self.exec_stmt(body, loop_env)
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEF
        if op == "break":
            raise _Break()
        if op == "continue":
            raise _Continue()
        if op == "empty":
            return UNDEF
        raise JsSyntaxError(f"unknown statement {op}")

    # ---- expressions ----

    def eval(self, node, env: Env):
        op = node[0]
        if op == "num":
            return node[1]
        if op == "str":
            return node[1]
        if op == "bool":
            return node[1]
        if op == "null":
            return None
        if op == "undef":
            return UNDEF
        if op == "name":
            return env.get(node[1])
        if op == "tpl":
            out = []
            for kind, payload in node[1]:
                if kind == "str":
                    out.append(payload)
                else:
                    out.append(js_str(self.eval(payload, env)))
            return "".join(out)
        if op == "array":
            out = []
            for item in node[1]:
                if item[0] == "spread":
                    out.extend(self.eval(item[1], env))
                else:
                    out.append(self.eval(item, env))
            return out
        if op == "object":
            return {k: self.eval(v, env) for k, v in node[1]}
        if op == "arrow":
            return JsFunction(node[1], node[2], env, self)
        if op == "cond":
            return (
                self.eval(node[2], env)
                if js_truthy(self.eval(node[1], env))
                else self.eval(node[3], env)
            )
        if op == "logic":
            left = self.eval(node[2], env)
            if node[1] == "&&":
                return self.eval(node[3], env) if js_truthy(left) else left
            if node[1] == "||":
                return left if js_truthy(left) else self.eval(node[3], env)
            # ??
            return (
                self.eval(node[3], env) if left is None or left is UNDEF else left
            )
        if op == "bin":
            return self.binop(node[1], self.eval(node[2], env), self.eval(node[3], env))
        if op == "unary":
            v = self.eval(node[2], env)
            if node[1] == "!":
                return not js_truthy(v)
            if node[1] == "-":
                return -js_num(v)
            return js_num(v)
        if op == "typeof":
            try:
                v = self.eval(node[1], env)
            except JsError:
                return "undefined"
            if v is UNDEF:
                return "undefined"
            if v is None:
                return "object"
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, float):
                return "number"
            if isinstance(v, str):
                return "string"
            if callable(v):
                return "function"
            return "object"
        if op in ("preincr", "postincr"):
            target = node[2]
            old = js_num(self.eval(target, env))
            new = old + (1 if node[1] == "++" else -1)
            self.assign_to(target, new, env)
            return new if op == "preincr" else old
        if op == "assign":
            _, aop, target, rhs = node
            val = self.eval(rhs, env)
            if aop != "=":
                cur = self.eval(target, env)
                val = self.binop(aop[0], cur, val)
            self.assign_to(target, val, env)
            return val
        if op == "comma":
            self.eval(node[1], env)
            return self.eval(node[2], env)
        if op == "member":
            obj = self.eval(node[1], env)
            if node[3] and (obj is None or obj is UNDEF):  # ?.
                return UNDEF
            return self.member_get(obj, node[2])
        if op == "index":
            obj = self.eval(node[1], env)
            idx = self.eval(node[2], env)
            return self.index_get(obj, idx)
        if op == "optindex":
            obj = self.eval(node[1], env)
            if obj is None or obj is UNDEF:
                return UNDEF
            return self.index_get(obj, self.eval(node[2], env))
        if op == "call":
            return self.eval_call(node, env)
        raise JsSyntaxError(f"unknown expression {op}")

    def binop(self, op: str, a, b):
        if op == "+":
            if isinstance(a, str) or isinstance(b, str):
                return js_str(a) + js_str(b)
            return js_num(a) + js_num(b)
        if op == "-":
            return js_num(a) - js_num(b)
        if op == "*":
            return js_num(a) * js_num(b)
        if op == "/":
            na, nb = js_num(a), js_num(b)
            if nb == 0:
                if na == 0 or na != na:
                    return math.nan
                return math.copysign(math.inf, na) * math.copysign(1, nb)
            return na / nb
        if op == "%":
            na, nb = js_num(a), js_num(b)
            if nb == 0 or na != na or nb != nb or abs(na) == math.inf:
                return math.nan
            return math.fmod(na, nb)  # JS % truncates toward zero
        if op == "**":
            return js_num(a) ** js_num(b)
        if op == "===":
            return js_eq_strict(a, b)
        if op == "!==":
            return not js_eq_strict(a, b)
        if op == "==":
            return js_eq_loose(a, b)
        if op == "!=":
            return not js_eq_loose(a, b)
        if op in ("<", "<=", ">", ">="):
            if isinstance(a, str) and isinstance(b, str):
                pass
            else:
                a, b = js_num(a), js_num(b)
                if a != a or b != b:
                    return False
            return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]
        raise JsSyntaxError(f"unknown operator {op}")

    def assign_to(self, target, val, env: Env):
        if target[0] == "name":
            env.set(target[1], val)
        elif target[0] == "member":
            obj = self.eval(target[1], env)
            if not isinstance(obj, dict):
                raise JsError(
                    f"TypeError: cannot set property {target[2]!r} on "
                    f"{js_str(obj)}"
                )
            obj[target[2]] = val
        elif target[0] == "index":
            obj = self.eval(target[1], env)
            idx = self.eval(target[2], env)
            if isinstance(obj, list):
                i = int(js_num(idx))
                while len(obj) <= i:
                    obj.append(UNDEF)
                obj[i] = val
            elif isinstance(obj, dict):
                obj[js_str(idx)] = val
            else:
                raise JsError("TypeError: cannot index-assign on non-object")
        else:
            raise JsSyntaxError("bad assignment target")

    def member_get(self, obj, prop: str):
        if obj is UNDEF or obj is None:
            raise JsError(
                f"TypeError: cannot read properties of {js_str(obj)} "
                f"(reading {prop!r})"
            )
        if isinstance(obj, dict):
            return obj.get(prop, UNDEF)
        if isinstance(obj, list):
            if prop == "length":
                return float(len(obj))
            m = _array_method(obj, prop)
            if m is not None:
                return m
            return UNDEF
        if isinstance(obj, str):
            if prop == "length":
                return float(len(obj))
            m = _string_method(obj, prop)
            if m is not None:
                return m
            return UNDEF
        if isinstance(obj, (float, bool)):
            m = _number_method(js_num(obj), prop)
            if m is not None:
                return m
            return UNDEF
        if callable(obj):
            return UNDEF
        raise JsError(f"TypeError: cannot read {prop!r} of {js_str(obj)}")

    def index_get(self, obj, idx):
        if isinstance(obj, list):
            if isinstance(idx, str):
                return self.member_get(obj, idx)
            i = int(js_num(idx))
            if 0 <= i < len(obj):
                return obj[i]
            return UNDEF
        if isinstance(obj, str):
            if isinstance(idx, float):
                i = int(idx)
                return obj[i] if 0 <= i < len(obj) else UNDEF
            return self.member_get(obj, js_str(idx))
        if isinstance(obj, dict):
            return obj.get(js_str(idx), UNDEF)
        if obj is UNDEF or obj is None:
            raise JsError(
                f"TypeError: cannot read properties of {js_str(obj)}"
            )
        return UNDEF

    def eval_call(self, node, env: Env):
        _, callee, raw_args = node
        args = []
        for a in raw_args:
            if a[0] == "spread":
                spread = self.eval(a[1], env)
                if not isinstance(spread, (list, str)):
                    raise JsError("TypeError: spread of non-iterable")
                args.extend(spread)
            else:
                args.append(self.eval(a, env))
        if callee[0] == "member":
            obj = self.eval(callee[1], env)
            if callee[3] and (obj is None or obj is UNDEF):
                return UNDEF
            fn = self.member_get(obj, callee[2])
            if not callable(fn):
                raise JsError(
                    f"TypeError: {callee[2]} is not a function "
                    f"(on {js_str(obj)[:40]})"
                )
            return fn(*args)
        fn = self.eval(callee, env)
        if not callable(fn):
            name = callee[1] if callee[0] == "name" else js_str(fn)
            raise JsError(f"TypeError: {name} is not a function")
        return fn(*args)


# ---------------------------------------------------------------------------
# Method tables
# ---------------------------------------------------------------------------


def _call1(fn, *args):
    """Invoke a JS callback that may take fewer args than provided.

    JsFunction already ignores surplus args (JS semantics). Native
    callables (Number, or a Python lambda injected by a test adapter)
    are trimmed to their declared positional arity so e.g.
    ``arr.map(Number)`` works — JS ignores surplus call arguments, a
    Python def raises TypeError on them."""
    if isinstance(fn, JsFunction):
        return fn(*args)
    try:
        sig = inspect.signature(fn)
    except (ValueError, TypeError):
        return fn(*args)
    max_pos = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            max_pos += 1
        elif p.kind == p.VAR_POSITIONAL:
            return fn(*args)
    return fn(*args[:max_pos])


def _array_method(arr: list, prop: str):
    def push(*vals):
        arr.extend(vals)
        return float(len(arr))

    def pop():
        return arr.pop() if arr else UNDEF

    def sort(cmp=None):
        if cmp is None:
            arr.sort(key=_sort_key_default)
        else:
            import functools

            arr.sort(key=functools.cmp_to_key(
                lambda a, b: -1 if js_num(_call1(cmp, a, b)) < 0
                else (1 if js_num(_call1(cmp, a, b)) > 0 else 0)))
        return arr

    def reduce(fn, *init):
        if not arr and not init:
            raise JsError("TypeError: reduce of empty array with no initial value")
        acc_set = bool(init)
        acc = init[0] if init else arr[0]
        start = 0 if acc_set else 1
        for i in range(start, len(arr)):
            acc = _call1(fn, acc, arr[i], float(i))
        return acc

    def find(fn):
        for i, x in enumerate(arr):
            if js_truthy(_call1(fn, x, float(i))):
                return x
        return UNDEF

    table = {
        "push": push,
        "pop": pop,
        "map": lambda fn: [_call1(fn, x, float(i)) for i, x in enumerate(arr)],
        "filter": lambda fn: [
            x for i, x in enumerate(arr) if js_truthy(_call1(fn, x, float(i)))
        ],
        "forEach": lambda fn: (
            [_call1(fn, x, float(i)) for i, x in enumerate(arr)], UNDEF
        )[1],
        "join": lambda sep=",": js_str(sep).join(
            "" if x in (None, UNDEF) else js_str(x) for x in arr
        ),
        "slice": lambda *a: arr[_slice(arr, *a)],
        "concat": lambda *vals: arr + [
            y for v in vals for y in (v if isinstance(v, list) else [v])
        ],
        "indexOf": lambda v: float(
            next((i for i, x in enumerate(arr) if js_eq_strict(x, v)), -1)
        ),
        "includes": lambda v: any(js_eq_strict(x, v) for x in arr),
        "some": lambda fn: any(
            js_truthy(_call1(fn, x, float(i))) for i, x in enumerate(arr)
        ),
        "every": lambda fn: all(
            js_truthy(_call1(fn, x, float(i))) for i, x in enumerate(arr)
        ),
        "reduce": reduce,
        "sort": sort,
        "find": find,
        "fill": lambda v: ([arr.__setitem__(i, v) for i in range(len(arr))], arr)[1],
        "reverse": lambda: (arr.reverse(), arr)[1],
        "flat": lambda: [
            y for x in arr for y in (x if isinstance(x, list) else [x])
        ],
    }
    return table.get(prop)


def _slice(seq, start=0.0, end=None):
    n = len(seq)
    s = int(js_num(start))
    e = n if end is None or end is UNDEF else int(js_num(end))
    if s < 0:
        s += n
    if e < 0:
        e += n
    return slice(max(0, s), max(0, e))


def _string_method(s: str, prop: str):
    table = {
        "slice": lambda *a: s[_slice(s, *a)],
        "split": lambda sep=UNDEF: list(s) if sep in ("", None)
        else ([s] if sep is UNDEF else s.split(js_str(sep))),
        "padStart": lambda w, fill=" ": s.rjust(int(js_num(w)), js_str(fill) or " "),
        "padEnd": lambda w, fill=" ": s.ljust(int(js_num(w)), js_str(fill) or " "),
        "repeat": lambda n: s * int(js_num(n)),
        "includes": lambda sub: js_str(sub) in s,
        "startsWith": lambda sub: s.startswith(js_str(sub)),
        "endsWith": lambda sub: s.endswith(js_str(sub)),
        "toUpperCase": lambda: s.upper(),
        "toLowerCase": lambda: s.lower(),
        "trim": lambda: s.strip(),
        "charCodeAt": lambda i=0.0: float(ord(s[int(js_num(i))]))
        if 0 <= int(js_num(i)) < len(s) else math.nan,
        "indexOf": lambda sub: float(s.find(js_str(sub))),
        "replace": lambda old, new: s.replace(js_str(old), js_str(new), 1),
        "toFixed": None,  # numbers only
        "toString": lambda: s,
        "concat": lambda *a: s + "".join(js_str(x) for x in a),
    }
    return table.get(prop)


def _number_method(v: float, prop: str):
    def to_fixed(digits=0.0):
        d = int(js_num(digits))
        if v != v:
            return "NaN"
        return f"{v:.{d}f}"

    return {"toFixed": to_fixed, "toString": lambda: js_num_str(v)}.get(prop)


def _parse_float(v) -> float:
    m = re.match(r"\s*[+-]?(\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?", js_str(v))
    return float(m.group(0)) if m else math.nan


def _parse_int(v, base=10.0) -> float:
    m = re.match(r"\s*[+-]?\d+", js_str(v))
    if not m:
        return math.nan
    try:
        return float(int(m.group(0), int(js_num(base)) or 10))
    except ValueError:
        return math.nan


def _json_stringify(v) -> str:
    import json as _json

    def conv(x):
        if x is UNDEF:
            return None
        if isinstance(x, float) and x.is_integer() and abs(x) < 1e15:
            return int(x)
        if isinstance(x, list):
            return [conv(y) for y in x]
        if isinstance(x, dict):
            return {k: conv(y) for k, y in x.items() if y is not UNDEF}
        if callable(x):
            return None
        return x

    return _json.dumps(conv(v), separators=(",", ":"))


# ---------------------------------------------------------------------------
# Convenience entry point
# ---------------------------------------------------------------------------


def load(src: str) -> Interp:
    """Parse + execute a script; returns the interpreter with the
    script's top-level functions available via .call(name, *args)."""
    interp = Interp()
    interp.run(src)
    return interp
