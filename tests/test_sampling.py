"""On-device temperature / top-k sampling (serving.sample_tokens).

Keys fold (request id, token index) — NOT a global step counter — so a
request's sampled stream is a pure function of (seed, prompt, params):
batch composition, slot assignment and scheduler choice cannot change
it. That invariant is what tests/test_scheduler.py pins end to end;
here it is pinned at the sampler itself.
"""

import jax
import jax.numpy as jnp

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import ServeConfig, ServingEngine, sample_tokens

KEY = jax.random.PRNGKey(0)
CFG = ModelConfig(
    vocab=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2, d_ff=64, max_seq=32
)

RIDS = jnp.arange(4, dtype=jnp.int32)
CTRS = jnp.zeros((4,), jnp.int32)


def logits_batch():
    return jax.random.normal(jax.random.PRNGKey(7), (4, 64)) * 3.0


def test_temperature_zero_is_argmax():
    logits = logits_batch()
    out = sample_tokens(logits, KEY, RIDS, CTRS, jnp.zeros((4,)),
                        jnp.zeros((4,), jnp.int32))
    assert (out == jnp.argmax(logits, axis=-1)).all()


def test_top_k_one_is_argmax_even_when_hot():
    logits = logits_batch()
    out = sample_tokens(logits, KEY, RIDS, CTRS,
                        jnp.full((4,), 5.0), jnp.ones((4,), jnp.int32))
    assert (out == jnp.argmax(logits, axis=-1)).all()


def test_top_k_restricts_support():
    logits = logits_batch()
    k = 3
    top3 = jnp.argsort(-logits, axis=-1)[:, :k]
    for ctr in range(30):
        out = sample_tokens(logits, KEY, RIDS, jnp.full((4,), ctr, jnp.int32),
                            jnp.full((4,), 2.0), jnp.full((4,), k, jnp.int32))
        for row in range(4):
            assert int(out[row]) in top3[row].tolist()


def test_sampling_is_reproducible_and_varies_with_counter():
    logits = logits_batch()
    temps = jnp.full((4,), 1.5)
    topk = jnp.zeros((4,), jnp.int32)
    ctr3 = jnp.full((4,), 3, jnp.int32)
    a = sample_tokens(logits, KEY, RIDS, ctr3, temps, topk)
    b = sample_tokens(logits, KEY, RIDS, ctr3, temps, topk)
    assert (a == b).all()  # same (rid, index) -> same tokens
    outs = {
        tuple(sample_tokens(logits, KEY, RIDS,
                            jnp.full((4,), c, jnp.int32),
                            temps, topk).tolist())
        for c in range(20)
    }
    assert len(outs) > 1  # the token index actually advances the stream


def test_streams_differ_per_request_id():
    """Two requests at the same token index draw from DIFFERENT key
    streams — the rid is folded in, not just the index."""
    logits = jnp.tile(logits_batch()[0], (4, 1))  # identical rows
    temps = jnp.full((4,), 1.5)
    topk = jnp.zeros((4,), jnp.int32)
    cols = [
        tuple(sample_tokens(logits, KEY, RIDS,
                            jnp.full((4,), c, jnp.int32),
                            temps, topk)[r].item() for c in range(16))
        for r in range(4)
    ]
    assert len(set(cols)) > 1


def test_row_position_does_not_change_the_draw():
    """The draw depends only on (rid, index, logits row) — NOT on which
    batch row (slot) the request occupies or who shares the batch. This
    is the sampler-level form of schedule independence."""
    logits = logits_batch()
    temps = jnp.full((4,), 1.5)
    topk = jnp.zeros((4,), jnp.int32)
    ctr = jnp.full((4,), 5, jnp.int32)
    full = sample_tokens(logits, KEY, RIDS, ctr, temps, topk)
    perm = jnp.asarray([2, 0, 3, 1])
    permuted = sample_tokens(logits[perm], KEY, RIDS[perm], ctr,
                             temps, topk)
    assert (permuted == full[perm]).all()
    # Batch of one == the same row inside a batch of four.
    solo = sample_tokens(logits[1:2], KEY, RIDS[1:2], ctr[:1],
                         temps[:1], topk[:1])
    assert int(solo[0]) == int(full[1])


def test_mixed_greedy_and_sampled_slots():
    logits = logits_batch()
    temps = jnp.array([0.0, 5.0, 0.0, 5.0])
    greedy = jnp.argmax(logits, axis=-1)
    out = sample_tokens(logits, KEY, RIDS, jnp.full((4,), 9, jnp.int32),
                        temps, jnp.zeros((4,), jnp.int32))
    assert int(out[0]) == int(greedy[0])
    assert int(out[2]) == int(greedy[2])


def test_engine_end_to_end_sampled():
    engine = ServingEngine(cfg=ServeConfig(model=CFG, slots=2, prefill_len=8))
    r = engine.submit([1, 2, 3], max_new=6, temperature=1.0, top_k=8)
    g = engine.submit([1, 2, 3], max_new=6)  # greedy alongside
    while not (r.done.is_set() and g.done.is_set()):
        engine.step()
    assert len(r.output) >= 6
    assert all(0 <= t < CFG.vocab for t in r.output)
    # Greedy request is unaffected by its sampled neighbor: rerunning the
    # same greedy prompt on a fresh engine gives the same stream.
    engine2 = ServingEngine(cfg=ServeConfig(model=CFG, slots=2, prefill_len=8))
    g2 = engine2.submit([1, 2, 3], max_new=6)
    while not g2.done.is_set():
        engine2.step()
    assert g2.output == g.output
