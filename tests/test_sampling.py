"""On-device temperature / top-k sampling (serving.sample_tokens)."""

import jax
import jax.numpy as jnp

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.serving import ServeConfig, ServingEngine, sample_tokens

KEY = jax.random.PRNGKey(0)
CFG = ModelConfig(
    vocab=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2, d_ff=64, max_seq=32
)


def logits_batch():
    return jax.random.normal(jax.random.PRNGKey(7), (4, 64)) * 3.0


def test_temperature_zero_is_argmax():
    logits = logits_batch()
    out = sample_tokens(logits, KEY, jnp.uint32(1), jnp.zeros((4,)),
                        jnp.zeros((4,), jnp.int32))
    assert (out == jnp.argmax(logits, axis=-1)).all()


def test_top_k_one_is_argmax_even_when_hot():
    logits = logits_batch()
    out = sample_tokens(logits, KEY, jnp.uint32(1),
                        jnp.full((4,), 5.0), jnp.ones((4,), jnp.int32))
    assert (out == jnp.argmax(logits, axis=-1)).all()


def test_top_k_restricts_support():
    logits = logits_batch()
    k = 3
    top3 = jnp.argsort(-logits, axis=-1)[:, :k]
    for ctr in range(30):
        out = sample_tokens(logits, KEY, jnp.uint32(ctr),
                            jnp.full((4,), 2.0), jnp.full((4,), k, jnp.int32))
        for row in range(4):
            assert int(out[row]) in top3[row].tolist()


def test_sampling_is_reproducible_and_varies_with_counter():
    logits = logits_batch()
    temps = jnp.full((4,), 1.5)
    topk = jnp.zeros((4,), jnp.int32)
    a = sample_tokens(logits, KEY, jnp.uint32(3), temps, topk)
    b = sample_tokens(logits, KEY, jnp.uint32(3), temps, topk)
    assert (a == b).all()  # same key+counter -> same tokens
    outs = {
        tuple(sample_tokens(logits, KEY, jnp.uint32(c), temps, topk).tolist())
        for c in range(20)
    }
    assert len(outs) > 1  # the counter actually advances the stream


def test_mixed_greedy_and_sampled_slots():
    logits = logits_batch()
    temps = jnp.array([0.0, 5.0, 0.0, 5.0])
    greedy = jnp.argmax(logits, axis=-1)
    out = sample_tokens(logits, KEY, jnp.uint32(9), temps,
                        jnp.zeros((4,), jnp.int32))
    assert int(out[0]) == int(greedy[0])
    assert int(out[2]) == int(greedy[2])


def test_engine_end_to_end_sampled():
    engine = ServingEngine(cfg=ServeConfig(model=CFG, slots=2, prefill_len=8))
    r = engine.submit([1, 2, 3], max_new=6, temperature=1.0, top_k=8)
    g = engine.submit([1, 2, 3], max_new=6)  # greedy alongside
    while not (r.done.is_set() and g.done.is_set()):
        engine.step()
    assert len(r.output) >= 6
    assert all(0 <= t < CFG.vocab for t in r.output)
    # Greedy request is unaffected by its sampled neighbor: rerunning the
    # same greedy prompt on a fresh engine gives the same stream.
    engine2 = ServingEngine(cfg=ServeConfig(model=CFG, slots=2, prefill_len=8))
    g2 = engine2.submit([1, 2, 3], max_new=6)
    while not g2.done.is_set():
        engine2.step()
    assert g2.output == g.output
