"""Speculative decoding tests (tpumon.loadgen.speculative).

The load-bearing invariant: under greedy decoding, speculative output is
IDENTICAL to plain decode no matter how good or bad the draft model is —
only the dispatch count changes. Both directions are pinned: a perfect
draft (self-speculation) accepts everything, a mismatched draft still
produces the same tokens.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import pytest

from tpumon.loadgen.model import ModelConfig, init_params
from tpumon.loadgen.serving import (
    ServeConfig,
    ServingEngine,
    decode_step,
    init_cache,
    prefill,
)
from tpumon.loadgen.speculative import decode_block, greedy_accept_len

# float32 compute so plain and speculative paths argmax identically
# (bfloat16 reassociation across different dispatch shapes could flip
# near-ties and make the equality tests flaky).
SMALL = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=64,
                    compute_dtype="float32")


def _prefilled(cfg: ServeConfig, params, prompts):
    cache = init_cache(cfg)
    for slot, prompt in enumerate(prompts):
        toks = jnp.asarray(
            prompt + [0] * (cfg.prefill_len - len(prompt)), jnp.int32)
        cache, _ = prefill(cfg, params, cache, toks,
                           jnp.int32(len(prompt)), jnp.int32(slot))
    return cache


class TestDecodeBlock:
    def test_t1_matches_decode_step(self):
        cfg = ServeConfig(model=SMALL, slots=2, prefill_len=8)
        params = init_params(SMALL, jax.random.PRNGKey(0))
        prompts = [[3, 5, 7], [11, 13, 17, 19]]
        cache_a = _prefilled(cfg, params, prompts)
        cache_b = jax.tree.map(jnp.copy, cache_a)
        feed = jnp.asarray([21, 23], jnp.int32)
        pos = jnp.asarray([3, 4], jnp.int32)
        _, la = decode_step(cfg, params, cache_a, feed, pos)
        _, lb = decode_block(cfg, params, cache_b, feed[:, None], pos)
        assert jnp.allclose(la, lb[:, 0], atol=1e-5)

    def test_block_matches_sequential_steps(self):
        """T sequential decode_steps == one decode_block over the same
        tokens: identical logits at every position and identical cache."""
        cfg = ServeConfig(model=SMALL, slots=2, prefill_len=8)
        params = init_params(SMALL, jax.random.PRNGKey(1))
        prompts = [[2, 4, 6, 8], [10, 12]]
        cache_seq = _prefilled(cfg, params, prompts)
        cache_blk = jax.tree.map(jnp.copy, cache_seq)
        tokens = jnp.asarray([[30, 31, 32], [40, 41, 42]], jnp.int32)
        pos0 = jnp.asarray([4, 2], jnp.int32)

        seq_logits = []
        for t in range(3):
            cache_seq, lg = decode_step(
                cfg, params, cache_seq, tokens[:, t], pos0 + t)
            seq_logits.append(lg)
        cache_blk, blk_logits = decode_block(
            cfg, params, cache_blk, tokens, pos0)
        for t in range(3):
            assert jnp.allclose(seq_logits[t], blk_logits[:, t], atol=1e-4)
        for name in ("k", "v"):
            assert jnp.allclose(
                cache_seq[name], cache_blk[name], atol=1e-5)


def _engine_outputs(prompts, max_new=12, **cfg_kw):
    eng = ServingEngine(cfg=ServeConfig(
        model=SMALL, slots=2, prefill_len=8, **cfg_kw))
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    return eng, [r.output for r in reqs]


PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7], [2, 7]]


class TestSpeculativeEngine:
    def test_self_speculation_matches_plain_and_accepts_all(self):
        _, plain = _engine_outputs(PROMPTS)
        eng, spec = _engine_outputs(PROMPTS, spec_len=4)
        assert spec == plain
        assert eng.spec_rounds_total > 0
        # Perfect draft: every proposal accepted, so rounds shrink by
        # ~spec_len+1 vs plain's one-token steps.
        assert eng.spec_accepted_total == eng.spec_proposed_total

    def test_weak_draft_is_still_lossless(self):
        draft = dataclasses.replace(SMALL, n_layers=1, d_ff=64)
        _, plain = _engine_outputs(PROMPTS)
        eng, spec = _engine_outputs(PROMPTS, spec_len=3, draft_model=draft)
        assert spec == plain  # the speculative-decoding contract
        assert eng.spec_proposed_total > 0
        assert eng.spec_accepted_total <= eng.spec_proposed_total

    def test_layer_truncated_draft_shares_target_weights(self):
        """--spec-draft-layers: a draft that is a pure layer truncation
        of the target gets the target's bottom layers + embed/head, not
        random weights (random agreement ~1/vocab makes the whole
        speculative path meaningless)."""
        draft = dataclasses.replace(SMALL, n_layers=1)
        _, plain = _engine_outputs(PROMPTS)
        eng, spec = _engine_outputs(PROMPTS, spec_len=3, draft_model=draft)
        assert spec == plain  # lossless regardless of draft quality
        assert eng.draft_params["layers"][0] is eng.params["layers"][0]
        assert eng.draft_params["embed"] is eng.params["embed"]
        assert len(eng.draft_params["layers"]) == 1
        assert eng.spec_proposed_total > 0

    def test_acceptance_rises_with_draft_depth(self):
        """Acceptance responds to draft quality: a 2-of-3-layer
        truncation agrees more than 1-of-3. Deterministic given the
        fixed seed + greedy decode."""
        deep = dataclasses.replace(SMALL, n_layers=3)

        def accept_frac(draft_layers: int) -> float:
            eng = ServingEngine(cfg=ServeConfig(
                model=deep, slots=2, prefill_len=8, spec_len=3,
                draft_model=dataclasses.replace(deep,
                                                n_layers=draft_layers)))
            reqs = [eng.submit(p, max_new=12) for p in PROMPTS]
            eng.drain()
            assert all(r.done.is_set() for r in reqs)
            return eng.spec_accepted_total / max(1, eng.spec_proposed_total)

        assert accept_frac(1) < accept_frac(2)

    def test_fewer_target_dispatches_than_plain(self):
        eng_plain, _ = _engine_outputs(PROMPTS, max_new=16)
        eng_spec, _ = _engine_outputs(PROMPTS, max_new=16, spec_len=4)
        assert eng_spec.decode_steps_total < eng_plain.decode_steps_total

    def test_temperature_slot_in_spec_batch(self):
        eng = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=3))
        greedy = eng.submit([3, 1, 4], max_new=8)
        sampled = eng.submit([9, 2, 6], max_new=8, temperature=0.8,
                             top_k=16)
        eng.drain()
        assert len(greedy.output) == 9 and len(sampled.output) == 9
        assert all(0 <= t < SMALL.vocab for t in sampled.output)
        # Greedy slot still matches the plain-engine result even when a
        # sampling request shares its batch.
        _, plain = _engine_outputs([[3, 1, 4]], max_new=8)
        assert greedy.output == plain[0]

    def test_all_temperature_batch_skips_spec_rounds(self):
        """Spec rounds for temperature-only batches are pure overhead
        (zero drafts acceptable) — the engine must fall back to plain."""
        eng = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=3))
        eng.submit([3, 1, 4], max_new=6, temperature=0.9)
        eng.submit([9, 2, 6], max_new=6, temperature=0.7)
        eng.drain()
        assert eng.spec_rounds_total == 0

    def test_draft_catchup_after_plain_fallback(self):
        """Plain-step fallbacks advance the sequence without the draft
        cache; when spec rounds resume the draft must be caught up or
        self-speculation acceptance silently collapses."""
        eng = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=3))
        greedy = eng.submit([3, 1, 4, 1], max_new=20)
        # Force plain fallbacks directly, then let spec rounds resume.
        # (Admission assigns + chunk-prefills; fallbacks only make
        # sense for slots that finished prefill — drain it first.)
        for _ in range(4):
            eng._admit()
            for s in range(eng.cfg.slots):
                eng._drain_prefill_slot(s)
            active = [s for s in range(eng.cfg.slots) if eng._slots[s]]
            eng._plain_step(active)
        assert eng._draft_pos[0] < eng._host_positions[0]  # hole exists
        eng.drain()
        assert greedy.done.is_set()
        assert eng.spec_rounds_total > 0
        # Self-speculating draft, so after catch-up every proposal must
        # still be accepted — catch-up failure would show up right here.
        assert eng.spec_accepted_total == eng.spec_proposed_total
        _, plain = _engine_outputs([[3, 1, 4, 1]], max_new=20)
        assert greedy.output == plain[0]

    def test_draft_as_deep_as_target_rejected(self):
        """A draft with >= the target's layers silently truncates to
        the target itself (acceptance tautologically 100%) — refuse."""
        for n in (2, 3):
            with pytest.raises(ValueError, match="shallower"):
                ServingEngine(cfg=ServeConfig(
                    model=SMALL, slots=2, prefill_len=8, spec_len=3,
                    draft_model=dataclasses.replace(SMALL, n_layers=n)))

    def test_draft_vocab_mismatch_rejected(self):
        bad = dataclasses.replace(SMALL, vocab=SMALL.vocab * 2)
        with pytest.raises(ValueError, match="vocab"):
            ServingEngine(cfg=ServeConfig(
                model=SMALL, slots=2, prefill_len=8, spec_len=2,
                draft_model=bad))

    def test_negative_spec_len_rejected(self):
        with pytest.raises(ValueError, match="spec_len"):
            ServingEngine(cfg=ServeConfig(
                model=SMALL, slots=2, prefill_len=8, spec_len=-1))

    def test_spec_metrics_exported(self):
        eng, _ = _engine_outputs(PROMPTS, spec_len=4)
        text = eng.metrics_text()
        assert "tpumon_serving_spec_rounds" in text
        assert "tpumon_serving_spec_proposed" in text
        assert "tpumon_serving_spec_accepted" in text

    def test_weight_bytes_counts_only_nonaliased_draft(self):
        """The gauge reports bytes actually resident in HBM: a
        self-speculating draft (shared params) adds nothing; the
        layer-truncated draft aliases EVERY leaf of the target (engine
        init slices the target's layers) so it too adds nothing; only a
        genuinely distinct draft (separate arrays) adds its bytes
        (r04 advisor finding: counting the truncated draft wholesale
        overstated resident HBM)."""
        from tpumon.loadgen.quant import param_bytes

        def weight_gauge(eng):
            for line in eng.metrics_text().splitlines():
                if line.startswith("tpumon_serving_weight_bytes"):
                    return float(line.split()[-1])
            raise AssertionError("gauge missing")

        base = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8))
        selfspec = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=2))
        truncated = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=2,
            draft_model=dataclasses.replace(SMALL, n_layers=1)))
        # Different d_ff -> the random-init (non-aliasing) draft branch
        # (drafts must also be shallower than the target).
        distinct = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=2,
            draft_model=dataclasses.replace(SMALL, n_layers=1, d_ff=64)))
        assert weight_gauge(selfspec) == weight_gauge(base)
        assert weight_gauge(truncated) == weight_gauge(base)
        assert weight_gauge(distinct) == weight_gauge(base) + param_bytes(
            distinct.draft_params)


def test_greedy_accept_len():
    assert greedy_accept_len([1, 2, 3], [1, 2, 3, 9]) == 3
    assert greedy_accept_len([1, 2, 3], [1, 9, 3, 9]) == 1
    assert greedy_accept_len([1, 2, 3], [9, 9, 9, 9]) == 0
    assert greedy_accept_len([], [7]) == 0


class TestPromptLookup:
    """spec_source='prompt' (tpumon.loadgen.prompt_lookup): n-gram
    proposals from the request's own context, no draft model — lossless
    under greedy regardless of guess quality, and high-acceptance when
    the continuation actually repeats."""

    def test_ngram_propose_copies_repeats(self):
        from tpumon.loadgen.prompt_lookup import ngram_propose

        # Period-4 sequence: the trailing 3-gram recurs one period back
        # and its continuation is the period's next tokens.
        ctx = [1, 2, 3, 4] * 3
        assert ngram_propose(ctx, 4) == [1, 2, 3, 4]
        assert ngram_propose(ctx, 6) == [1, 2, 3, 4, 1, 2]  # cycles
        # Unique context: no prior n-gram, fallback repeats last token.
        assert ngram_propose([5, 6, 7, 8], 3) == [8, 8, 8]
        assert ngram_propose([], 2) == [0, 0]
        assert ngram_propose([1, 2], 0) == []

    def test_ngram_propose_prefers_longest_match(self):
        from tpumon.loadgen.prompt_lookup import ngram_propose

        # 3-gram [7,8,9] recurs with continuation 50; a mere 1-gram [9]
        # also recurs earlier with continuation 60 — the longer match
        # must win.
        ctx = [9, 60, 7, 8, 9, 50, 1, 7, 8, 9]
        assert ngram_propose(ctx, 1) == [50]

    def test_engine_lossless_vs_plain(self):
        _, plain = _engine_outputs(PROMPTS)
        eng, spec = _engine_outputs(PROMPTS, spec_len=3,
                                    spec_source="prompt")
        assert spec == plain  # the speculative contract, any proposer
        assert eng.spec_rounds_total > 0
        assert eng.draft_params is None  # no draft machinery at all

    def test_engine_lossless_paged(self):
        _, plain = _engine_outputs(PROMPTS, kv_layout="paged")
        _, spec = _engine_outputs(PROMPTS, spec_len=3,
                                  spec_source="prompt", kv_layout="paged")
        assert spec == plain

    def test_rejects_draft_model_combo(self):
        with pytest.raises(ValueError, match="spec_source"):
            ServingEngine(cfg=ServeConfig(
                model=SMALL, slots=2, prefill_len=8, spec_len=2,
                spec_source="prompt",
                draft_model=dataclasses.replace(SMALL, n_layers=1)))
        with pytest.raises(ValueError, match="spec_source"):
            ServingEngine(cfg=ServeConfig(
                model=SMALL, slots=2, prefill_len=8, spec_len=2,
                spec_source="telepathy"))

    def test_tp_mesh_paged_prompt_lookup(self):
        """prompt-lookup + paged over a tensor-parallel mesh: the r05
        _shard_paged_jits prompt branch (verify over the sharded pool)."""
        import numpy as np

        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs multiple devices")
        mesh = Mesh(np.array(devs[:2]).reshape(2), ("model",))
        _, ref = _engine_outputs(PROMPTS, kv_layout="paged")
        eng = ServingEngine(cfg=ServeConfig(
            model=SMALL, slots=2, prefill_len=8, spec_len=3,
            spec_source="prompt", kv_layout="paged"), mesh=mesh)
        reqs = [eng.submit(p, max_new=12) for p in PROMPTS]
        eng.drain()
        assert [r.output for r in reqs] == ref
