"""K8s transport layer over real HTTP (VERDICT r03 missing #3).

The pure PodList parser is golden-tested in tests/test_k8s.py; what was
never executed is the transport underneath: the list request path, the
in-cluster auth resolution, the long-lived chunked watch stream with
its resume/re-list protocol, and recovery when the apiserver dies.
Here tests/fakes.FakeK8sWatchApi speaks the actual wire protocol on an
ephemeral port and ApiPodSource / PodWatcher / K8sCollector are driven
against it. Reference behavior being re-offered:
/root/reference/monitor_server.js:97-114 queries a live cluster (via
execSync kubectl); tpumon talks to the API server directly.
"""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from tests.fakes import FakeK8sWatchApi
from tests.test_k8s import pod_doc
from tpumon.collectors.k8s import ApiPodSource, K8sCollector, PodWatcher


def wait_until(cond, timeout_s: float = 8.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def item(name, phase="Running", rv="1", ns="default"):
    doc = pod_doc(name=name, ns=ns, phase=phase)
    doc["metadata"]["resourceVersion"] = rv
    return doc


def ev(kind, obj):
    return {"type": kind, "object": obj}


@pytest.fixture()
def api():
    backend = FakeK8sWatchApi(pods=[item("a", rv="5"), item("b", rv="6")])
    yield backend
    backend.close()


# ------------------------------------------------------------- list path


def test_api_pod_source_lists_over_http(api):
    pods = asyncio.run(ApiPodSource(api_url=api.url).fetch_pod_list())
    assert {p["metadata"]["name"] for p in pods["items"]} == {"a", "b"}
    assert api.list_calls == 1


def test_api_collector_mode_end_to_end(api):
    sample = asyncio.run(K8sCollector(mode="api", api_url=api.url).collect())
    assert sample.ok
    assert {p["name"] for p in sample.data} == {"a", "b"}
    assert sample.data[0]["status"] == "Running"


def test_list_error_is_reported_not_raised(api):
    api.close()  # nothing listening any more
    sample = asyncio.run(K8sCollector(mode="api", api_url=api.url).collect())
    assert not sample.ok and sample.data == []
    assert "ApiPodSource" in sample.error


# ----------------------------------------------------------------- auth


def test_in_cluster_resolution_builds_auth(tmp_path, monkeypatch):
    """In-cluster mode: https URL from the service env, Bearer token
    from the mounted service account, TLS context from its CA."""
    from tpumon.collectors import k8s as k8s_mod

    (tmp_path / "token").write_text("sekrit-token\n")
    monkeypatch.setattr(k8s_mod, "SA_DIR", str(tmp_path))
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "6443")
    base, headers, ctx = ApiPodSource()._resolve()
    assert base == "https://10.0.0.1:6443"
    assert headers == {"Authorization": "Bearer sekrit-token"}
    assert ctx is None  # no ca.crt present

    monkeypatch.delenv("KUBERNETES_SERVICE_HOST")
    with pytest.raises(RuntimeError, match="not in-cluster"):
        ApiPodSource()._resolve()


class _AuthedSource(ApiPodSource):
    """api_url transport with injected auth headers — proves _fetch
    actually sends what _resolve returns."""

    def _resolve(self):
        return self.api_url, {"Authorization": "Bearer tok123"}, None


def test_bearer_token_sent_and_checked():
    backend = FakeK8sWatchApi(pods=[item("a")], token="tok123")
    try:
        pods = asyncio.run(
            _AuthedSource(api_url=backend.url).fetch_pod_list())
        assert [p["metadata"]["name"] for p in pods["items"]] == ["a"]
        assert backend.seen_auth[-1] == "Bearer tok123"
        # And the unauthenticated path is truly rejected by the fake.
        with pytest.raises(Exception):
            asyncio.run(ApiPodSource(api_url=backend.url).fetch_pod_list())
        assert backend.auth_failures == 1
    finally:
        backend.close()


# ----------------------------------------------------------- watch path


def test_watch_stream_applies_events_and_resumes(api):
    # Connection 1: one pod added, one pod fails, then a clean stream
    # end (server-side timeout). Connection 2 holds open.
    api.push_watch_script([
        ev("ADDED", item("c", rv="11")),
        ev("MODIFIED", item("a", phase="Failed", rv="12")),
        ev("BOOKMARK", {"metadata": {"resourceVersion": "12"}}),
    ])
    api.push_watch_script(["HOLD"])
    w = PodWatcher(api_url=api.url, reconnect_delay_s=0.05)
    try:
        w.start()
        wait_until(lambda: len(api.watch_calls) >= 2, what="reconnect")
        assert w.synced
        doc, interim = w.snapshot()
        names = {i["metadata"]["name"] for i in doc["items"]}
        assert names == {"a", "b", "c"}
        # The excursion a poller would miss: a recorded Failed phase.
        assert interim["default/a"] == ["Failed"]
        # First watch resumed from the LIST's rv; after the clean end,
        # the second resumed from the last event's rv — no re-list.
        assert api.watch_calls[0]["resourceVersion"] == ["10"]
        assert api.watch_calls[1]["resourceVersion"] == ["12"]
        assert api.list_calls == 1
        assert w.last_error is None
    finally:
        w.stop()


def test_watch_error_event_forces_relist(api):
    """The 410 Gone / expired-resourceVersion protocol: an ERROR event
    must tear down the stream and re-list before watching again."""
    api.push_watch_script([
        ev("ERROR", {"kind": "Status", "code": 410, "reason": "Expired"}),
    ])
    api.push_watch_script(["HOLD"])
    w = PodWatcher(api_url=api.url, reconnect_delay_s=0.05)
    try:
        w.start()
        wait_until(lambda: api.list_calls >= 2, what="re-list after 410")
        wait_until(lambda: len(api.watch_calls) >= 2, what="re-watch")
        assert w.reconnects >= 1
        # Resynced: the map still serves and the error is cleared.
        wait_until(lambda: w.last_error is None, what="error cleared")
        doc, _ = w.snapshot()
        assert {i["metadata"]["name"] for i in doc["items"]} == {"a", "b"}
    finally:
        w.stop()


def test_watch_recovers_after_apiserver_restart(api):
    api.push_watch_script(["HOLD"])
    w = PodWatcher(api_url=api.url, reconnect_delay_s=0.05)
    try:
        w.start()
        wait_until(lambda: w.synced, what="initial sync")
        port = api.port
        api.close()  # apiserver dies mid-watch
        wait_until(lambda: w.last_error is not None, what="stream error")
        # Collector keeps serving the last-synced state, degraded.
        c = K8sCollector(mode="watch", api_url=f"http://127.0.0.1:{port}")
        c._watcher = w
        sample = c._watch_sample()
        assert not sample.ok and "degraded" in sample.error
        assert {p["name"] for p in sample.data} == {"a", "b"}
        # Apiserver comes back on the same port with a changed world.
        api2 = FakeK8sWatchApi(pods=[item("z", rv="20")], port=port)
        api2.rv = 21
        api2.push_watch_script(["HOLD"])
        try:
            wait_until(lambda: api2.list_calls >= 1 and w.last_error is None,
                       what="resync after restart")
            doc, _ = w.snapshot()
            assert {i["metadata"]["name"] for i in doc["items"]} == {"z"}
            sample = c._watch_sample()
            assert sample.ok and {p["name"] for p in sample.data} == {"z"}
        finally:
            api2.close()
    finally:
        w.stop()


def test_watch_collector_surfaces_deleted_pod_excursion(api):
    """A pod that vanishes between samples still reports its final
    excursion — exactly the event watch mode exists to catch."""
    api.push_watch_script([
        ev("MODIFIED", item("b", phase="Failed", rv="11")),
        ev("DELETED", item("b", phase="Failed", rv="12")),
    ])
    api.push_watch_script(["HOLD"])
    c = K8sCollector(mode="watch", api_url=api.url)
    c._watcher = PodWatcher(api_url=api.url, reconnect_delay_s=0.05)
    try:
        c._watcher.start()
        wait_until(lambda: len(api.watch_calls) >= 2, what="events applied")
        sample = c._watch_sample()
        assert sample.ok
        by_name = {p["name"]: p for p in sample.data}
        assert set(by_name) == {"a", "b"}
        assert by_name["b"]["status"] == "Deleted"
        assert by_name["b"]["interim_phases"] == ["Failed", "Deleted"]
        # Next sample: the excursion was drained, b is gone entirely.
        sample = c._watch_sample()
        assert {p["name"] for p in sample.data} == {"a"}
    finally:
        c._watcher.stop()
