"""Alert silencing (Alertmanager-style mutes).

A silence is a key prefix + expiry: matching alerts leave the served
severity buckets and stop paging webhooks, but their lifecycle (active
keys, fired/resolved timeline) keeps recording, and silences survive
restarts via the state snapshot.
"""

from __future__ import annotations

import asyncio
import json
import urllib.request

from tpumon.alerts import AlertEngine


def hot_host(pct=97.0):
    return {"cpu": {"percent": pct}}


def test_silenced_alert_leaves_buckets_but_keeps_lifecycle():
    e = AlertEngine()
    out = e.evaluate(host=hot_host(), now=1000.0)
    assert len(out["critical"]) == 1

    e.silence("host.cpu", 600, now=1001.0)
    out = e.evaluate(host=hot_host(), now=1002.0)
    assert out["critical"] == []
    assert [a["key"] for a in e.last_silenced] == ["host.cpu.critical"]
    # Lifecycle continues: condition clears -> resolved event recorded.
    e.evaluate(host=hot_host(10.0), now=1003.0)
    assert any(
        ev["state"] == "resolved" and ev["key"] == "host.cpu.critical"
        for ev in e.events
    )


def test_silence_expires_and_unsilence():
    e = AlertEngine()
    e.silence("host.cpu", 10, now=1000.0)
    e.evaluate(host=hot_host(), now=1005.0)
    assert e.last["critical"] == []
    out = e.evaluate(host=hot_host(), now=1011.0)  # expired
    assert len(out["critical"]) == 1
    assert e.silences == {}  # expired silences pruned

    e.silence("host.", 600, now=1012.0)
    assert e.unsilence("host.") is True
    assert e.unsilence("host.") is False
    out = e.evaluate(host=hot_host(), now=1013.0)
    assert len(out["critical"]) == 1


def test_prefix_matches_family_of_keys():
    e = AlertEngine()
    e.silence("host.", 600, now=1000.0)
    out = e.evaluate(
        host={"cpu": {"percent": 97.0}, "memory": {"percent": 88.0}}, now=1001.0
    )
    assert out["critical"] == [] and out["serious"] == []
    assert len(e.last_silenced) == 2


def test_silences_survive_state_round_trip():
    e = AlertEngine()
    e.silence("chip.", 3600, now=1000.0)
    e2 = AlertEngine()
    e2.load_state(json.loads(json.dumps(e.to_state())))
    assert "chip." in e2.silences


def test_silenced_events_do_not_page_webhooks():
    from tpumon.app import build
    from tpumon.config import load_config

    cfg = load_config(
        env={
            "TPUMON_ACCEL_BACKEND": "none",
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host",
            "TPUMON_PORT": "0",
        }
    )
    sampler, _ = build(cfg)
    rxed: list = []
    sampler.notifier = type("N", (), {"notify": lambda self, ev: rxed.append(ev)})()
    sampler.engine.silence("host.cpu", 3600)
    sampler.engine.evaluate(host=hot_host())
    sampler._notify_new_events()
    assert rxed == []
    # A non-silenced alert still pages.
    sampler.engine.evaluate(host={"memory": {"percent": 97.0}})
    sampler._notify_new_events()
    assert len(rxed) == 1
    assert all(e["key"].startswith("host.memory") for e in rxed[0])


def test_silence_http_routes():
    from tests.test_server_api import serve, run_app

    sampler, server = serve()
    loop = asyncio.new_event_loop()
    port = loop.run_until_complete(run_app(sampler, server))
    try:

        def post(path, payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(payload).encode(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        def run(fn, *a):
            return loop.run_until_complete(asyncio.to_thread(fn, *a))

        status, body = run(post, "/api/silence", {"key": "chip.", "duration": "2h"})
        assert status == 200 and body["silenced"] == "chip."
        assert "chip." in sampler.engine.silences

        def get_alerts():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/alerts"
            ) as r:
                return json.loads(r.read())

        alerts = run(get_alerts)
        assert alerts["silences"][0]["key"] == "chip."

        status, body = run(post, "/api/unsilence", {"key": "chip."})
        assert status == 200 and body["existed"] is True

        # Error paths: missing key, bad duration, POST elsewhere.
        assert run(post, "/api/silence", {})[0] == 400
        assert run(post, "/api/silence", {"key": "x", "duration": "nope"})[0] == 400
        assert run(post, "/api/alerts", {"key": "x"})[0] == 405
    finally:
        loop.run_until_complete(server.stop())
        loop.close()


def test_suppressed_fire_repages_after_silence_expires():
    # Regression: an alert that fires during a silence and outlives it
    # must page once the silence ends (Alertmanager re-notify semantics).
    e = AlertEngine()
    e.silence("host.cpu", 10, now=1000.0)
    e.evaluate(host=hot_host(), now=1001.0)  # fires, suppressed
    fired = [ev for ev in e.events if ev["state"] == "fired"]
    assert len(fired) == 1
    e.evaluate(host=hot_host(), now=1011.0)  # silence expired, still hot
    fired = [ev for ev in e.events if ev["state"] == "fired"]
    assert len(fired) == 2  # fresh event => fresh seq => webhook delivery
    assert fired[1]["seq"] > fired[0]["seq"]
    # No third fire on the next tick.
    e.evaluate(host=hot_host(), now=1012.0)
    assert len([ev for ev in e.events if ev["state"] == "fired"]) == 2


def test_resolution_of_silenced_alert_still_pages():
    from tpumon.app import build
    from tpumon.config import load_config

    cfg = load_config(
        env={
            "TPUMON_ACCEL_BACKEND": "none",
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host",
            "TPUMON_PORT": "0",
        }
    )
    sampler, _ = build(cfg)
    rxed: list = []
    sampler.notifier = type("N", (), {"notify": lambda self, ev: rxed.append(ev)})()
    sampler.engine.evaluate(host=hot_host())
    sampler._notify_new_events()  # fire pages
    sampler.engine.silence("host.cpu", 3600)
    sampler.engine.evaluate(host=hot_host(10.0))  # clears under silence
    sampler._notify_new_events()
    resolved = [e for batch in rxed for e in batch if e["state"] == "resolved"]
    assert len(resolved) == 1  # the incident closes despite the silence


def test_cross_origin_post_refused():
    from tests.test_server_api import serve, run_app

    sampler, server = serve()
    loop = asyncio.new_event_loop()
    port = loop.run_until_complete(run_app(sampler, server))
    try:

        def post(headers):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/silence",
                data=b'{"key": "x.", "duration": "1h"}',
                headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status
            except urllib.error.HTTPError as err:
                return err.code

        def run(fn, *a):
            return loop.run_until_complete(asyncio.to_thread(fn, *a))

        assert run(post, {"Origin": "http://evil.example"}) == 403
        assert "x." not in sampler.engine.silences
        # Same-origin browser POST and origin-less curl both pass.
        assert run(post, {"Origin": f"http://127.0.0.1:{port}"}) == 200
        assert run(post, {}) == 200
    finally:
        loop.run_until_complete(server.stop())
        loop.close()


def test_null_origin_post_refused():
    # Regression: "Origin: null" (sandboxed iframe / data: URL) must be
    # treated as cross-origin, not waved through.
    from tests.test_server_api import serve, run_app

    sampler, server = serve()
    loop = asyncio.new_event_loop()
    port = loop.run_until_complete(run_app(sampler, server))
    try:

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/silence",
                data=b'{"key": "y.", "duration": "1h"}',
                headers={"Origin": "null"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status
            except urllib.error.HTTPError as err:
                return err.code

        assert loop.run_until_complete(asyncio.to_thread(post)) == 403
        assert "y." not in sampler.engine.silences
    finally:
        loop.run_until_complete(server.stop())
        loop.close()
