"""Paged KV serving mode (tpumon.loadgen.paged_kv + engine wiring).

Load-bearing invariants: paged greedy outputs are identical to dense
mode's; pages are reclaimed on completion and reused; pool exhaustion
blocks admission (backpressure) instead of corrupting or crashing.
"""

import dataclasses

import pytest

from tpumon.loadgen.model import ModelConfig
from tpumon.loadgen.paged_kv import PageAllocator
from tpumon.loadgen.serving import ServeConfig, ServingEngine

SMALL = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=64,
                    compute_dtype="float32")


def make_engine(layout="paged", pool_pages=0, slots=2, **kw):
    return ServingEngine(cfg=ServeConfig(
        model=SMALL, slots=slots, prefill_len=8, kv_layout=layout,
        pool_pages=pool_pages, **kw))


PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7, 9, 3, 2], [2, 7]]


class TestAllocator:
    def test_alloc_release_roundtrip(self):
        a = PageAllocator(5)
        got = a.alloc(3)
        assert len(got) == 3 and a.free_pages == 2
        assert a.alloc(3) is None and a.free_pages == 2  # no change
        a.release(got)
        assert a.free_pages == 5


class TestPagedEngine:
    def test_outputs_match_dense(self):
        dense = make_engine("dense")
        d_reqs = [dense.submit(p, max_new=10) for p in PROMPTS]
        dense.drain()
        paged = make_engine("paged")
        p_reqs = [paged.submit(p, max_new=10) for p in PROMPTS]
        paged.drain()
        assert [r.output for r in p_reqs] == [r.output for r in d_reqs]

    def test_outputs_match_dense_when_slots_neq_kv_heads(self):
        """slots != n_kv_heads: the decode scatter's batch/head axis
        orientation can't hide behind a same-size broadcast (the bug
        class the [B, nkv, hd] comment in paged_kv documents)."""
        dense = make_engine("dense", slots=3)
        d = [dense.submit(p, max_new=8) for p in PROMPTS]
        dense.drain()
        paged = make_engine("paged", slots=3)
        g = [paged.submit(p, max_new=8) for p in PROMPTS]
        paged.drain()
        assert [r.output for r in g] == [r.output for r in d]

    def test_long_prompt_chunked_prefill_matches_dense(self):
        prompt = list(range(1, 30))  # 4 chunks of 8
        dense = make_engine("dense")
        rd = dense.submit(prompt, max_new=8)
        dense.drain()
        paged = make_engine("paged")
        rp = paged.submit(prompt, max_new=8)
        paged.drain()
        assert rp.output == rd.output

    def test_pages_freed_and_reused(self):
        eng = make_engine("paged", pool_pages=17)  # 16 usable + trash
        total = eng.allocator.free_pages
        for _ in range(3):
            reqs = [eng.submit(p, max_new=6) for p in PROMPTS]
            eng.drain()
            assert all(r.done.is_set() for r in reqs)
            assert eng.allocator.free_pages == total  # all reclaimed

    def test_exhaustion_blocks_admission_then_recovers(self):
        # Pool fits exactly one request's reservation at a time:
        # prompt 5 + max_new 10 -> ceil(15/8) = 2 pages; pool = 2+trash.
        eng = make_engine("paged", pool_pages=3)
        a = eng.submit(PROMPTS[0], max_new=10)
        b = eng.submit(PROMPTS[1], max_new=10)
        eng.drain()
        # Both eventually complete (b waited for a's pages) and outputs
        # still match dense mode.
        assert a.done.is_set() and b.done.is_set()
        dense = make_engine("dense")
        da = dense.submit(PROMPTS[0], max_new=10)
        db = dense.submit(PROMPTS[1], max_new=10)
        dense.drain()
        assert a.output == da.output and b.output == db.output

    def test_freed_slot_writes_cannot_corrupt_live_requests(self):
        """After one slot completes, its stale batched-decode writes go
        to the trash page — a still-running request's output must match
        a solo run exactly."""
        solo = make_engine("paged")
        r_solo = solo.submit(PROMPTS[2], max_new=20)
        solo.drain()

        eng = make_engine("paged")
        short = eng.submit(PROMPTS[0], max_new=2)  # completes early
        long = eng.submit(PROMPTS[2], max_new=20)
        eng.drain()
        assert short.done.is_set()
        assert long.output == r_solo.output

    def test_oversize_reservation_rejected_not_wedged(self):
        """A request that could never fit the pool is rejected at
        submit; requests behind it still run."""
        eng = make_engine("paged", pool_pages=3)  # 2 usable
        big = eng.submit([1] * 5, max_new=30)  # needs 5 pages > 2
        assert big.done.is_set() and big.output == []
        ok = eng.submit(PROMPTS[1], max_new=10)  # needs 2 pages
        eng.drain()
        assert ok.done.is_set() and len(ok.output) == 11
        assert eng.rejected_total == 1

    def test_negative_max_new_clamped(self):
        eng = make_engine("paged")
        r = eng.submit([1, 2], max_new=-20)
        eng.drain()
        assert r.done.is_set() and len(r.output) == 1  # like max_new=0

    def test_pool_gauges_exported(self):
        eng = make_engine("paged", pool_pages=9)
        text = eng.metrics_text()
        assert "tpumon_serving_kv_pages_total 8" in text
        assert "tpumon_serving_kv_pages_free 8" in text

    def test_rejects_unknown_layout(self):
        # Speculative decoding and prefix caching both compose with
        # paged KV since r04 (tests/test_paged_prefix.py executes the
        # page-sharing and paged-verify paths).
        with pytest.raises(ValueError, match="kv_layout"):
            make_engine("diagonal")

    def test_sampling_and_streaming_compose(self):
        eng = make_engine("paged")
        r1 = eng.submit(PROMPTS[0], max_new=6, temperature=0.8, top_k=16)
        r2 = eng.submit(PROMPTS[1], max_new=6, stream=True)
        eng.drain()
        assert len(r1.output) == 7
        toks = []
        while True:
            t = r2.stream.get(timeout=5)
            if t is None:
                break
            toks.append(t)
        assert toks == r2.output

    def test_lifecycle_fuzz_conserves_pages(self):
        """Randomized submit/step churn: page accounting must balance
        exactly whenever the engine is idle, and every request must
        terminate (no leak, no double-free, no wedge)."""
        import random

        rng = random.Random(7)
        eng = make_engine("paged", pool_pages=9, slots=3)
        usable = eng.allocator.free_pages
        live = []
        for round_ in range(6):
            for _ in range(rng.randint(1, 5)):
                n = rng.randint(1, 20)
                live.append(eng.submit(
                    [rng.randrange(128) for _ in range(n)],
                    max_new=rng.randint(0, 12),
                    temperature=rng.choice([0.0, 0.8])))
            for _ in range(rng.randint(1, 30)):
                eng.step()
        eng.drain()
        assert all(r.done.is_set() for r in live)
        assert eng.allocator.free_pages == usable
        assert sorted(set(eng.allocator._free)) == sorted(
            eng.allocator._free)  # no duplicate page ids in free list

    def test_memory_is_smaller_than_dense(self):
        """The point of the mode: pool sized to half the dense rows."""
        import jax

        dense = make_engine("dense")
        paged = make_engine("paged", pool_pages=9)
        dense_bytes = sum(x.nbytes for x in jax.tree.leaves(dense.cache))
        paged_bytes = sum(x.nbytes for x in jax.tree.leaves(paged.pool))
        assert paged_bytes < 0.6 * dense_bytes


class TestPagedKernelAttention:
    """ServeConfig.paged_attn='kernel': the Pallas paged-attention
    kernel (tpumon.ops.paged_attention) as the engine's decode read
    path, replacing the XLA table gather (interpret mode on CPU)."""

    def test_outputs_match_gather_path(self):
        gather = make_engine("paged")
        g = [gather.submit(p, max_new=10) for p in PROMPTS]
        gather.drain()
        kernel = make_engine("paged", paged_attn="kernel")
        k = [kernel.submit(p, max_new=10) for p in PROMPTS]
        kernel.drain()
        assert [r.output for r in k] == [r.output for r in g]

    def test_block_decode_runs_kernel_per_round(self):
        """decode_block>1 scans paged_decode_step, so every round of
        the fused loop goes through the kernel; outputs must match the
        plain-step kernel engine (and therefore dense)."""
        step = make_engine("paged", paged_attn="kernel")
        s = [step.submit(p, max_new=9) for p in PROMPTS]
        step.drain()
        blk = make_engine("paged", paged_attn="kernel", decode_block=4)
        b = [blk.submit(p, max_new=9) for p in PROMPTS]
        blk.drain()
        assert [r.output for r in b] == [r.output for r in s]

    def test_composes_with_speculative_verify(self):
        """spec verify (multi-token queries) stays on the gather path;
        the plain-step fallback uses the kernel — outputs must still
        match the gather engine exactly."""
        gather = make_engine("paged", spec_len=2)
        g = [gather.submit(p, max_new=8) for p in PROMPTS]
        gather.drain()
        kernel = make_engine("paged", spec_len=2, paged_attn="kernel")
        k = [kernel.submit(p, max_new=8) for p in PROMPTS]
        kernel.drain()
        assert [r.output for r in k] == [r.output for r in g]

    def test_fragmented_pool_outputs_stable(self):
        """Churn the pool (interleaved alloc/free scrambles the free
        list) and verify a post-churn request still matches a fresh
        engine — the kernel's table indirection must be layout-blind."""
        eng = make_engine("paged", paged_attn="kernel", pool_pages=13,
                          slots=3)
        for round_ in range(3):  # interleaved lifetimes fragment pages
            rs = [eng.submit(p, max_new=2 + 3 * (i % 2))
                  for i, p in enumerate(PROMPTS)]
            eng.drain()
            assert all(r.done.is_set() for r in rs)
        post = eng.submit(PROMPTS[2], max_new=12)
        eng.drain()
        fresh = make_engine("paged", paged_attn="kernel")
        ref = fresh.submit(PROMPTS[2], max_new=12)
        fresh.drain()
        assert post.output == ref.output

    def test_kernel_requires_paged_compute_pool(self):
        with pytest.raises(ValueError, match="paged_attn"):
            make_engine("dense", paged_attn="kernel")
        with pytest.raises(ValueError, match="paged_attn"):
            make_engine("paged", paged_attn="kernel", kv_dtype="int8")
        with pytest.raises(ValueError, match="paged_attn"):
            make_engine("paged", paged_attn="sideways")
