from tpumon.topology import ChipSample, normalize_chip_kind, slice_views


def chip(i, host="h0", slice_id="s0", **kw):
    defaults = dict(
        chip_id=f"{host}/chip-{i}",
        host=host,
        slice_id=slice_id,
        index=i,
        kind="v5e",
        mxu_duty_pct=50.0,
        hbm_used=8 * 2**30,
        hbm_total=16 * 2**30,
    )
    defaults.update(kw)
    return ChipSample(**defaults)


def test_normalize_chip_kind():
    assert normalize_chip_kind("TPU v5 lite") == "v5e"
    assert normalize_chip_kind("TPU v5p") == "v5p"
    assert normalize_chip_kind("TPU v4") == "v4"
    assert normalize_chip_kind("TPU v6e") == "v6e"


def test_hbm_pct():
    assert chip(0).hbm_pct == 50.0
    assert chip(0, hbm_used=None).hbm_pct is None
    assert chip(0, hbm_total=None).hbm_pct is None


def test_slice_views_rollup():
    chips = [chip(i, host=f"h{i // 2}") for i in range(4)]
    views = slice_views(chips, expected={"s0": 8})
    assert len(views) == 1
    v = views[0]
    assert v.reporting_chips == 4
    assert v.expected_chips == 8
    assert v.missing_chips == 4
    assert sorted(v.hosts) == ["h0", "h1"]
    assert v.mean("mxu_duty_pct") == 50.0


def test_slice_views_absent_expected_slice():
    views = slice_views([], expected={"ghost": 16})
    assert len(views) == 1
    assert views[0].slice_id == "ghost"
    assert views[0].missing_chips == 16


def test_slice_json_shape():
    v = slice_views([chip(0)], expected={})[0]
    j = v.to_json()
    assert j["slice"] == "s0"
    assert j["reporting_chips"] == 1
    assert j["missing_chips"] == 0
    assert j["mean_hbm_pct"] == 50.0
