from tpumon.topology import ChipSample, normalize_chip_kind, slice_views


def chip(i, host="h0", slice_id="s0", **kw):
    defaults = dict(
        chip_id=f"{host}/chip-{i}",
        host=host,
        slice_id=slice_id,
        index=i,
        kind="v5e",
        mxu_duty_pct=50.0,
        hbm_used=8 * 2**30,
        hbm_total=16 * 2**30,
    )
    defaults.update(kw)
    return ChipSample(**defaults)


def test_normalize_chip_kind():
    assert normalize_chip_kind("TPU v5 lite") == "v5e"
    assert normalize_chip_kind("TPU v5p") == "v5p"
    assert normalize_chip_kind("TPU v4") == "v4"
    assert normalize_chip_kind("TPU v6e") == "v6e"


def test_hbm_pct():
    assert chip(0).hbm_pct == 50.0
    assert chip(0, hbm_used=None).hbm_pct is None
    assert chip(0, hbm_total=None).hbm_pct is None


def test_slice_views_rollup():
    chips = [chip(i, host=f"h{i // 2}") for i in range(4)]
    views = slice_views(chips, expected={"s0": 8})
    assert len(views) == 1
    v = views[0]
    assert v.reporting_chips == 4
    assert v.expected_chips == 8
    assert v.missing_chips == 4
    assert sorted(v.hosts) == ["h0", "h1"]
    assert v.mean("mxu_duty_pct") == 50.0


def test_slice_views_absent_expected_slice():
    views = slice_views([], expected={"ghost": 16})
    assert len(views) == 1
    assert views[0].slice_id == "ghost"
    assert views[0].missing_chips == 16


def test_slice_json_shape():
    v = slice_views([chip(0)], expected={})[0]
    j = v.to_json()
    assert j["slice"] == "s0"
    assert j["reporting_chips"] == 1
    assert j["missing_chips"] == 0
    assert j["mean_hbm_pct"] == 50.0


# ---------------- pod -> chip attribution ------------------------------


def _chip(host, index, chip_id=None):
    from tpumon.topology import ChipSample

    return ChipSample(
        chip_id=chip_id or f"{host}/chip-{index}",
        host=host,
        slice_id="slice-0",
        index=index,
        kind="v5e",
    )


def test_attribute_single_pod_owns_all_host_chips():
    from tpumon.topology import attribute_pods

    chips = [_chip("tpu-host-0", i) for i in range(4)]
    pods = [
        {"namespace": "serving", "name": "js-0", "node": "tpu-host-0",
         "tpu_request": 4},
        {"namespace": "ml", "name": "cpu-job", "node": "cpu-node-1",
         "tpu_request": 0},
    ]
    out = attribute_pods(chips, pods)
    assert out == {c.chip_id: "serving/js-0" for c in chips}


def test_attribute_splits_chips_proportionally():
    from tpumon.topology import attribute_pods

    chips = [_chip("h0", i) for i in range(4)]
    pods = [
        {"namespace": "a", "name": "p1", "node": "h0", "tpu_request": 1},
        {"namespace": "a", "name": "p2", "node": "h0", "tpu_request": 3},
    ]
    out = attribute_pods(chips, pods)
    assert out["h0/chip-0"] == "a/p1"
    assert out["h0/chip-1"] == "a/p2"
    assert out["h0/chip-3"] == "a/p2"


def test_attribute_no_tpu_pods_or_no_chips():
    from tpumon.topology import attribute_pods

    assert attribute_pods([], [{"node": "h0", "tpu_request": 8}]) == {}
    assert attribute_pods([_chip("h0", 0)], None) == {}
    # Pod on a host with no reporting chips attributes nothing.
    assert attribute_pods(
        [_chip("h1", 0)],
        [{"namespace": "x", "name": "p", "node": "h0", "tpu_request": 8}],
    ) == {}


def test_attribute_excess_chips_stay_unowned():
    # Regression: chips beyond the host's requested total must not be
    # clamped onto the last pod.
    from tpumon.topology import attribute_pods

    chips = [_chip("h0", i) for i in range(8)]
    pods = [{"namespace": "a", "name": "p", "node": "h0", "tpu_request": 2}]
    out = attribute_pods(chips, pods)
    assert len(out) == 2
    assert "h0/chip-7" not in out


def test_attribute_stable_when_low_index_chips_vanish():
    # Regression: ownership keys off the chip's host-local index, so a
    # pod's surviving chips keep their owner when earlier chips die.
    from tpumon.topology import attribute_pods

    pods = [
        {"namespace": "a", "name": "p1", "node": "h0", "tpu_request": 4},
        {"namespace": "a", "name": "p2", "node": "h0", "tpu_request": 4},
    ]
    surviving = [_chip("h0", i) for i in range(4, 8)]  # p2's chips only
    out = attribute_pods(surviving, pods)
    assert all(v == "a/p2" for v in out.values()) and len(out) == 4


# ---------------- accelerator families (ISSUE 15) ----------------------


def test_accel_kind_defaults_and_json_roundtrip():
    from tpumon.collectors.accel_peers import chip_from_json

    c = chip(0)
    assert c.accel_kind == "tpu"  # the pre-upgrade meaning of every chip
    j = c.to_json()
    assert j["accel_kind"] == "tpu"
    assert chip_from_json(j).accel_kind == "tpu"
    g = chip(1, accel_kind="gpu", kind="a100")
    assert chip_from_json(g.to_json()).accel_kind == "gpu"
    # A pre-accel_kind peer's JSON omits the key entirely: default tpu.
    old = c.to_json()
    del old["accel_kind"]
    assert chip_from_json(old).accel_kind == "tpu"


def test_slice_view_accel_kind():
    views = slice_views(
        [chip(0), chip(1, accel_kind="gpu", kind="a100", slice_id="g0")],
        expected={"ghost": 4},
    )
    by_id = {v.slice_id: v for v in views}
    assert by_id["s0"].accel_kind == "tpu"
    assert by_id["g0"].accel_kind == "gpu"
    assert by_id["ghost"].accel_kind is None  # no chips, no family claim
    assert by_id["g0"].to_json()["accel_kind"] == "gpu"
    assert by_id["ghost"].to_json()["accel_kind"] is None


def test_wire_fields_append_only_contract():
    """accel_kind must stay the LAST wire column (append-only is what
    lets pre-upgrade peers decode new frames and new readers default
    old frames — the ISSUE 15 wire contract)."""
    from tpumon.topology import WIRE_FIELDS, chips_from_wire, chips_to_wire

    assert WIRE_FIELDS[-1] == "accel_kind"
    chips = [chip(0), chip(1, accel_kind="gpu", kind="h100")]
    w = chips_to_wire(chips)
    assert chips_from_wire(w) == chips
    old = {
        "v": w["v"],
        "fields": w["fields"][:-1],
        "rows": [r[:-1] for r in w["rows"]],
    }
    back = chips_from_wire(old)
    assert [c.accel_kind for c in back] == ["tpu", "tpu"]
