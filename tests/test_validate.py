"""Verdict logic of the hardware validation harness (VERDICT r1 #3).

The classifiers are pure functions over sampled counter values, so the
rise/fall/skip/fail paths are all pinned here without a chip; the
end-to-end path runs against the fake collector (synthetic counters =>
counter checks SKIP, serving check executes for real on CPU).
"""

from __future__ import annotations

import asyncio
import json

from tpumon.topology import ChipSample
from tpumon.validate import (
    CheckResult,
    classify_chips_visible,
    classify_hbm_response,
    classify_mxu_response,
    classify_serving,
    results_json,
    summarize,
    validate,
)

GIB = 2**30


def chip(idx=0, **kw):
    return ChipSample(
        chip_id=f"h0/chip-{idx}", host="h0", slice_id="s0", index=idx,
        kind="v5e", **kw,
    )


# ------------------------------------------------------------ chips

def test_chips_visible_pass_and_fail():
    assert classify_chips_visible([chip()]).verdict == "PASS"
    r = classify_chips_visible([])
    assert r.verdict == "FAIL" and "no chips" in r.detail


# ------------------------------------------------------------ hbm

def test_hbm_rise_and_fall_passes():
    r = classify_hbm_response(2 * GIB, 7 * GIB, 3 * GIB, synthetic=False)
    assert r.verdict == "PASS"
    assert "during fill" in r.detail and "after release" in r.detail


def test_hbm_no_rise_fails():
    r = classify_hbm_response(2 * GIB, 2.1 * GIB, None, synthetic=False)
    assert r.verdict == "FAIL" and "did not track" in r.detail


def test_hbm_counter_vanishes_during_fill_fails():
    assert (
        classify_hbm_response(2 * GIB, None, None, synthetic=False).verdict
        == "FAIL"
    )


def test_hbm_no_fall_is_noted_not_failed():
    # Allocator retention / coarse counters can hold the peak briefly;
    # the rise is the gate, the missing fall is recorded for the artifact.
    r = classify_hbm_response(2 * GIB, 7 * GIB, 7 * GIB, synthetic=False)
    assert r.verdict == "PASS" and "release not yet visible" in r.detail


def test_hbm_release_measurement_missing_still_passes_rise():
    # hbm_after None (collector raced the release): rise evidence stands.
    assert (
        classify_hbm_response(2 * GIB, 7 * GIB, None, synthetic=False).verdict
        == "PASS"
    )


def test_hbm_skip_paths():
    assert classify_hbm_response(None, None, None, False).verdict == "SKIP"
    r = classify_hbm_response(2 * GIB, 7 * GIB, 3 * GIB, synthetic=True)
    assert r.verdict == "SKIP" and "synthetic" in r.detail


# ------------------------------------------------------------ mxu

def test_mxu_rise_passes():
    r = classify_mxu_response(1.0, [2.0, 40.0, 80.0], synthetic=False)
    assert r.verdict == "PASS" and "peak 80.0%" in r.detail


def test_mxu_flat_fails():
    assert classify_mxu_response(1.0, [1.0, 1.2, None], False).verdict == "FAIL"


def test_mxu_absolute_floor():
    # A constant tiny counter (0.1 -> 0.4) must not pass just because it
    # moved: the peak must clear 5% absolute.
    assert classify_mxu_response(0.1, [0.4], False).verdict == "FAIL"
    assert classify_mxu_response(0.1, [6.0], False).verdict == "PASS"


def test_mxu_skip_paths():
    assert classify_mxu_response(None, [], False).verdict == "SKIP"
    assert classify_mxu_response(50.0, [90.0], True).verdict == "SKIP"


# ------------------------------------------------------------ serving

def test_serving_classification():
    assert classify_serving("all good", None).verdict == "PASS"
    assert classify_serving(None, ImportError("no jax")).verdict == "SKIP"
    r = classify_serving(None, AssertionError("no tokens counted"))
    assert r.verdict == "FAIL" and "no tokens" in r.detail


# ------------------------------------------------------------ summary

def test_summarize_exit_codes():
    ok = [CheckResult("a", "PASS", ""), CheckResult("b", "SKIP", "x")]
    assert summarize(ok)[1] == 0
    assert summarize(ok + [CheckResult("c", "FAIL", "y")])[1] == 1


def test_results_json_roundtrip():
    rs = [CheckResult("a", "PASS", "fine")]
    d = results_json(rs, backend="fake:v5e-8", seconds=1.23)
    # The artifact the driver reads must be plain JSON with verdicts.
    parsed = json.loads(json.dumps(d))
    assert parsed["exit"] == 0 and parsed["backend"] == "fake:v5e-8"
    assert parsed["checks"][0] == {
        "check": "a", "verdict": "PASS", "detail": "fine",
    }


# ------------------------------------------------------------ end-to-end

def test_validate_end_to_end_fake_backend():
    """Full harness against the fake collector: chips PASS, counter
    checks SKIP (synthetic), serving runs for real on this device."""
    results = asyncio.run(validate("fake:v5e-8"))
    by = {r.check: r for r in results}
    assert by["chips-visible"].verdict == "PASS"
    assert by["hbm-response"].verdict == "SKIP"
    assert by["mxu-response"].verdict == "SKIP"
    assert by["serving-engine"].verdict in ("PASS", "SKIP")
    if by["serving-engine"].verdict == "PASS":
        assert "outputs agree" in by["serving-engine"].detail
