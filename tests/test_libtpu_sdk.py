"""Golden tests for the libtpu SDK metric parsers + collector merge.

Every golden string below is taken verbatim from the official metric
``description()`` examples captured on real hardware (PROBE_libtpu.md),
so a libtpu grammar change shows up as a failing golden here rather than
as silently-empty panels.
"""

from __future__ import annotations

import asyncio

import pytest

from tpumon.collectors import run_collector
from tpumon.collectors.accel_jax import TEMP_UNAVAILABLE_NOTE, JaxTpuCollector
from tpumon.collectors.libtpu_sdk import (
    IciLink,
    LibtpuSdkSource,
    SdkSnapshot,
    ici_health_by_chip,
    parse_float_list,
    parse_ici_link_health,
    parse_int_list,
    parse_labeled_percentiles,
    parse_queue_sizes,
    parse_throttle_scores,
)


# ---------------------------------------------------------------- parsers

def test_parse_float_list_duty_cycle_golden():
    # duty_cycle_pct description example: [0.00, 20.00, 0.00, 0.00]
    assert parse_float_list(["0.00", "20.00", "0.00", "0.00"]) == {
        0: 0.0,
        1: 20.0,
        2: 0.0,
        3: 0.0,
    }


def test_parse_float_list_skips_junk():
    assert parse_float_list(["1.5", "garbage", "3"]) == {0: 1.5, 2: 3.0}
    assert parse_float_list([]) == {}


def test_parse_int_list_hbm_golden():
    # hbm_capacity_total example: [33550229504, ...] (31.24 GiB chips)
    data = ["33550229504", "33550229504", "33550229504", "33550229504"]
    assert parse_int_list(data) == {i: 33550229504 for i in range(4)}


def test_parse_int_list_hbm_usage_golden():
    # hbm_capacity_usage example: [1073741824, 0, 0, 0]
    assert parse_int_list(["1073741824", "0", "0", "0"]) == {
        0: 1073741824,
        1: 0,
        2: 0,
        3: 0,
    }


def test_parse_ici_link_health_golden():
    # ici_link_health example: ['tray1.chip3.ici0.int: 0',
    #                           'tray1.chip3.ici1.int: 10']
    links = parse_ici_link_health(
        ["tray1.chip3.ici0.int: 0", "tray1.chip3.ici1.int: 10"]
    )
    assert links == [
        IciLink(location="tray1.chip3.ici0.int", chip=3, port=0, score=0),
        IciLink(location="tray1.chip3.ici1.int", chip=3, port=1, score=10),
    ]
    # Worst-per-chip rollup: chip 3 carries the unusable link's score.
    assert ici_health_by_chip(links) == {3: 10}


def test_parse_ici_link_health_unknown_location():
    links = parse_ici_link_health(["weird-location: 4", "nonsense", "x: bad"])
    assert len(links) == 1
    assert links[0].score == 4 and links[0].chip is None
    assert ici_health_by_chip(links) == {-1: 4}


def test_parse_throttle_scores_golden():
    # tpu_throttle_score example: ['0-0', '1-1', '2-0', '3-0']
    assert parse_throttle_scores(["0-0", "1-1", "2-0", "3-0"]) == {
        0: 0,
        1: 1,
        2: 0,
        3: 0,
    }


def test_parse_labeled_percentiles_buffer_golden():
    # buffer_transfer_latency example:
    # [8MB+, 100.00, 200.00, 300.00, 400.00, 500.00]
    out = parse_labeled_percentiles(["8MB+, 100.00, 200.00, 300.00, 400.00, 500.00"])
    assert out == {
        "8MB+": {
            "mean": 100.0,
            "p50": 200.0,
            "p90": 300.0,
            "p95": 400.0,
            "p999": 500.0,
        }
    }


def test_parse_labeled_percentiles_collective_golden():
    # collective_e2e_latency example label: 2MB+-ALL_REDUCE
    out = parse_labeled_percentiles(
        ["2MB+-ALL_REDUCE, 100.00, 200.00, 300.00, 400.00, 500.00"]
    )
    assert list(out) == ["2MB+-ALL_REDUCE"]
    assert out["2MB+-ALL_REDUCE"]["p999"] == 500.0


def test_parse_labeled_percentiles_hlo_timing_golden():
    # hlo_execution_timing example label: tensorcore_0
    out = parse_labeled_percentiles(
        ["tensorcore_0, 100.00, 200.00, 300.00, 400.00, 500.00"]
    )
    assert out["tensorcore_0"]["mean"] == 100.0


def test_parse_queue_sizes_golden():
    # hlo_queue_size example: [tensorcore_0: 0, tensorcore_1: 10, ...]
    out = parse_queue_sizes(
        ["tensorcore_0: 0", "tensorcore_1: 10", "tensorcore_2: 20", "tensorcore_3: 30"]
    )
    assert out == {
        "tensorcore_0": 0,
        "tensorcore_1": 10,
        "tensorcore_2": 20,
        "tensorcore_3": 30,
    }


# ------------------------------------------------------------- source

class _FakeMetric:
    def __init__(self, data):
        self._data = data

    def data(self):
        return self._data


class _FakeTpuMonitoring:
    """Stands in for libtpu.sdk.tpumonitoring."""

    def __init__(self, payloads: dict[str, list[str]]):
        self.payloads = payloads

    def list_supported_metrics(self):
        return list(self.payloads)

    def get_metric(self, name):
        return _FakeMetric(self.payloads[name])


def _source_with(payloads: dict[str, list[str]]) -> LibtpuSdkSource:
    src = LibtpuSdkSource()
    src._mod = _FakeTpuMonitoring(payloads)
    src._supported = list(payloads)
    return src


def test_sdk_source_snapshot_merges_all_metrics():
    src = _source_with(
        {
            "duty_cycle_pct": ["12.50", "99.00"],
            "hbm_capacity_usage": ["1073741824", "0"],
            "hbm_capacity_total": ["17179869184", "17179869184"],
            "ici_link_health": ["tray0.chip0.ici0.int: 0", "tray0.chip1.ici0.int: 7"],
            "tpu_throttle_score": ["0-0", "1-3"],
            "hlo_queue_size": ["tensorcore_0: 2"],
            "buffer_transfer_latency": ["8MB+, 1.0, 2.0, 3.0, 4.0, 5.0"],
        }
    )
    snap = asyncio.run(src.snapshot())
    assert snap is not None
    assert snap.duty_pct == {0: 12.5, 1: 99.0}
    assert snap.hbm_used == {0: 1073741824, 1: 0}
    assert snap.hbm_total[0] == 17179869184
    assert snap.ici_health == {0: 0, 1: 7}
    assert snap.throttle == {0: 0, 1: 3}
    assert snap.extras["hlo_queue_size"] == {"tensorcore_0": 2}
    assert "8MB+" in snap.extras["buffer_transfer_latency"]


def test_sdk_source_all_empty_is_unavailable():
    """The axon-tunnel case from PROBE_libtpu.md: SDK importable, every
    metric answers [] — must read as 'source absent', not zeros."""
    src = _source_with({name: [] for name in ("duty_cycle_pct", "ici_link_health")})
    assert asyncio.run(src.snapshot()) is None


def test_sdk_source_missing_module_is_unavailable():
    src = LibtpuSdkSource()
    src._import_failed = True
    assert asyncio.run(src.snapshot()) is None


def test_sdk_source_tensorcore_util_fallback():
    src = _source_with(
        {"duty_cycle_pct": [], "tensorcore_util": ["0.00", "20.00"]}
    )
    snap = asyncio.run(src.snapshot())
    assert snap.duty_pct == {0: 0.0, 1: 20.0}


# --------------------------------------------------- collector merge

class _FakeDevice:
    platform = "tpu"
    device_kind = "TPU v5 lite"

    def __init__(self, idx):
        self.id = idx
        self.local_hardware_id = idx
        self.coords = (idx, 0, 0)

    def memory_stats(self):
        return {}


def _collector_with_sdk(snap: SdkSnapshot | None) -> JaxTpuCollector:
    c = JaxTpuCollector(hostname="testhost", slice_id="s0")
    c._devices = [_FakeDevice(0), _FakeDevice(1)]

    class _Sdk:
        async def snapshot(self):
            return snap

    class _Grpc:
        async def snapshot(self):
            return None

    c._sdk = _Sdk()
    c._client = _Grpc()
    return c


def test_accel_jax_merges_sdk_snapshot():
    snap = SdkSnapshot(
        duty_pct={0: 42.0, 1: 7.0},
        hbm_used={0: 2**30, 1: 0},
        hbm_total={0: 16 * 2**30, 1: 16 * 2**30},
        ici_health={0: 0, 1: 10},
        throttle={0: 0, 1: 5},
        extras={"hlo_queue_size": {"tensorcore_0": 1}},
    )
    c = _collector_with_sdk(snap)
    s = asyncio.run(run_collector(c))
    assert s.ok
    by_idx = {ch.index: ch for ch in s.data}
    assert by_idx[0].mxu_duty_pct == 42.0
    assert by_idx[0].hbm_used == 2**30
    assert by_idx[0].ici_link_health == 0
    assert by_idx[0].ici_link_up is True
    # Chip 1: unusable link (score 10) -> link down; throttled 50%.
    assert by_idx[1].ici_link_health == 10
    assert by_idx[1].ici_link_up is False
    assert by_idx[1].throttle_score == 5
    # temp is platform-unavailable and declared, not silently None.
    assert by_idx[0].temp_c is None
    assert TEMP_UNAVAILABLE_NOTE in s.notes
    assert c.last_extras == {"hlo_queue_size": {"tensorcore_0": 1}}


def test_accel_jax_clears_extras_when_sdk_disappears():
    """A dead workload's HLO queue/latency extras must not be served as
    current once the SDK stops reporting."""
    snap = SdkSnapshot(
        duty_pct={0: 1.0}, extras={"hlo_queue_size": {"tensorcore_0": 9}}
    )
    c = _collector_with_sdk(snap)
    asyncio.run(run_collector(c))
    assert c.last_extras
    # Workload exits: SDK answers all-empty -> snapshot None.
    async def gone():
        return None

    c._sdk.snapshot = gone
    asyncio.run(run_collector(c))
    assert c.last_extras == {}


def test_accel_jax_partial_sdk_falls_through_to_grpc_per_field():
    """An SDK snapshot reporting only link health (empty duty/HBM maps)
    must not preempt the gRPC source wholesale: duty/HBM fall through
    per-field while the SDK's ici_health is kept."""
    snap = SdkSnapshot(ici_health={0: 3, 1: 0})
    c = _collector_with_sdk(snap)

    class _Grpc:
        async def snapshot(self):
            return {
                "duty_pct": {0: 12.0, 1: 34.0},
                "hbm_used": {0: 2**30, 1: 2**31},
                "hbm_total": {0: 16 * 2**30, 1: 16 * 2**30},
            }

    c._client = _Grpc()
    s = asyncio.run(run_collector(c))
    assert s.ok
    by_idx = {ch.index: ch for ch in s.data}
    assert by_idx[0].ici_link_health == 3  # from SDK
    assert by_idx[0].mxu_duty_pct == 12.0  # from gRPC
    assert by_idx[1].hbm_used == 2**31  # from gRPC


def test_accel_jax_per_chip_sdk_gap_falls_through_to_grpc():
    """A NON-empty SDK map that covers only some chips must still pull
    the missing chips from gRPC (gap detection is per-chip, not
    per-family)."""
    snap = SdkSnapshot(duty_pct={0: 42.0}, hbm_used={0: 2**30},
                       hbm_total={0: 16 * 2**30})
    c = _collector_with_sdk(snap)

    class _Grpc:
        async def snapshot(self):
            return {
                "duty_pct": {0: 1.0, 1: 34.0},
                "hbm_used": {0: 1, 1: 2**31},
                "hbm_total": {0: 1, 1: 16 * 2**30},
            }

    c._client = _Grpc()
    s = asyncio.run(run_collector(c))
    assert s.ok
    by_idx = {ch.index: ch for ch in s.data}
    assert by_idx[0].mxu_duty_pct == 42.0  # SDK still wins where present
    assert by_idx[0].hbm_used == 2**30
    assert by_idx[1].mxu_duty_pct == 34.0  # gap filled from gRPC
    assert by_idx[1].hbm_used == 2**31
    assert by_idx[1].counter_source == "grpc"


def test_accel_jax_dark_sources_probe_off_tick_path():
    """After a source goes dark its probe cost must leave the sampler
    tick: re-probes ride a background task (BENCH_r02's 3.6x
    sampler-rate regression), and a source that comes alive is adopted
    on the next tick."""
    calls = {"sdk": 0, "grpc": 0}
    alive = {"sdk": False}

    c = JaxTpuCollector(hostname="testhost", slice_id="s0")
    c._devices = [_FakeDevice(0)]

    class _Sdk:
        async def snapshot(self):
            calls["sdk"] += 1
            return SdkSnapshot(duty_pct={0: 9.0}) if alive["sdk"] else None

    class _Grpc:
        async def snapshot(self):
            calls["grpc"] += 1
            return None

    c._sdk = _Sdk()
    c._client = _Grpc()

    async def main():
        await run_collector(c)  # first collect probes inline, goes dark
        assert calls["sdk"] == 1 and calls["grpc"] == 1
        for _ in range(28):  # collects 2..29: dark sources stay skipped
            await run_collector(c)
        assert calls["sdk"] == 1 and calls["grpc"] == 1
        alive["sdk"] = True
        await run_collector(c)  # collect 30 kicks the background probe
        assert c._reprobe_task is not None
        await c._reprobe_task
        assert calls["sdk"] == 2  # probed off-tick, found alive
        s = await run_collector(c)  # next tick adopts the source inline
        assert s.data[0].mxu_duty_pct == 9.0

    asyncio.run(main())


def test_accel_jax_unattributed_ici_links_hit_every_chip():
    """A bad link whose location lacks a chipN token (rolled up under -1)
    must surface on the host's chips, not vanish."""
    snap = SdkSnapshot(duty_pct={0: 1.0, 1: 1.0}, ici_health={-1: 7})
    c = _collector_with_sdk(snap)
    s = asyncio.run(run_collector(c))
    assert all(ch.ici_link_health == 7 for ch in s.data)


def test_alert_engine_owns_link_down_from_health_score():
    """health==10 alone (e.g. a fake-backend override that doesn't also
    flip ici_link_up) must still raise the critical link-down alert."""
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds
    from tpumon.topology import ChipSample

    chip = ChipSample(
        chip_id="h0/chip-0", host="h0", slice_id="s0", index=0, kind="v5e",
        ici_link_health=10,  # ici_link_up left at None
    )
    alerts = AlertEngine(Thresholds())._chip_alerts([chip])
    keys = {a.key for a in alerts}
    assert "chip.h0/chip-0.ici_down" in keys
    assert not any("ici_health" in k for k in keys)


def test_accel_jax_no_sdk_degrades_with_note():
    c = _collector_with_sdk(None)
    s = asyncio.run(run_collector(c))
    # No counter source at all: fields None, sample degraded but present.
    assert not s.ok
    assert all(ch.mxu_duty_pct is None for ch in s.data)
    assert all(ch.ici_link_health is None for ch in s.data)
    assert TEMP_UNAVAILABLE_NOTE in s.notes


# ------------------------------------------------------- alert rules

def test_ici_health_and_throttle_alerts():
    from tpumon.alerts import AlertEngine
    from tpumon.config import Thresholds
    from tpumon.topology import ChipSample

    def chip(idx, **kw):
        return ChipSample(
            chip_id=f"h0/chip-{idx}",
            host="h0",
            slice_id="s0",
            index=idx,
            kind="v5e",
            **kw,
        )

    engine = AlertEngine(Thresholds())
    chips = [
        chip(0, ici_link_health=0, throttle_score=0),  # healthy
        chip(1, ici_link_health=3),  # transient -> minor
        chip(2, ici_link_health=7),  # persistent -> serious
        chip(3, ici_link_health=10, ici_link_up=False),  # -> critical ici_down
        chip(4, throttle_score=3),  # ~30% -> minor
        chip(5, throttle_score=6),  # ~60% -> serious
        chip(6, throttle_score=9),  # ~90% -> critical
    ]
    alerts = engine._chip_alerts(chips)
    keys = {a.key: a.severity for a in alerts}
    assert keys.get("chip.h0/chip-1.ici_health.minor") == "minor"
    assert keys.get("chip.h0/chip-2.ici_health.serious") == "serious"
    assert keys.get("chip.h0/chip-3.ici_down") == "critical"
    # Score 10 must NOT also fire the degradation rule.
    assert not any("chip-3.ici_health" in k for k in keys)
    assert keys.get("chip.h0/chip-4.throttle.minor") == "minor"
    assert keys.get("chip.h0/chip-5.throttle.serious") == "serious"
    assert keys.get("chip.h0/chip-6.throttle.critical") == "critical"
    assert not any("chip-0." in k for k in keys)


def test_exporter_emits_runtime_extras():
    """SDK slice-level extras (HLO queue, latency percentiles) re-export
    as tpu_* gauges so Prometheus can record them."""
    from tpumon.config import Config
    from tpumon.exporter import render_exporter
    from tpumon.sampler import Sampler

    class _Accel:
        name = "accel"
        last_extras = {
            "hlo_queue_size": {"tensorcore_0": 3},
            "collective_e2e_latency": {
                "2MB+-ALL_REDUCE": {"mean": 100.0, "p50": 200.0,
                                    "p90": 300.0, "p95": 400.0,
                                    "p999": 500.0},
            },
        }

        async def collect(self):  # pragma: no cover - not sampled here
            raise NotImplementedError

    sampler = Sampler(Config(), accel=_Accel())
    text = render_exporter(sampler)
    assert 'tpu_hlo_queue_size{core="tensorcore_0"} 3' in text
    assert ('tpu_collective_e2e_latency_us{bucket="2MB+-ALL_REDUCE",'
            'quantile="p50"} 200' in text)
    # The mean is not a quantile: it rides its own series, and no sample
    # ever carries quantile="mean" (a reserved summary-type convention).
    assert ('tpu_collective_e2e_latency_us_mean{bucket="2MB+-ALL_REDUCE"} 100'
            in text)
    assert 'quantile="mean"' not in text


def test_exporter_emits_new_gauges():
    from tpumon.config import Config
    from tpumon.exporter import render_exporter
    from tpumon.sampler import Sampler
    from tpumon.collectors import Sample
    from tpumon.topology import ChipSample

    cfg = Config()
    sampler = Sampler(cfg)
    sampler.latest["accel"] = Sample(
        source="accel",
        ok=True,
        data=[
            ChipSample(
                chip_id="h0/chip-0",
                host="h0",
                slice_id="s0",
                index=0,
                kind="v5e",
                ici_link_health=7,
                throttle_score=2,
            )
        ],
    )
    text = render_exporter(sampler)
    assert 'tpu_ici_link_health_score{chip="h0/chip-0"' in text
    assert "tpu_ici_link_health_score" in text and " 7" in text
    assert 'tpu_throttle_score{chip="h0/chip-0"' in text


# ------------------------------------------------------- probe_sources


def test_probe_sources_reports_live_and_dark(tmp_path):
    """validate.py provenance (VERDICT r03 item #8): every counter
    source reports live/dark with a WHY, per source."""
    snap = SdkSnapshot(duty_pct={0: 42.0}, hbm_used={0: 2**30})
    c = _collector_with_sdk(snap)
    c._client.addr = "localhost:8431"
    c._client.last_error = None
    probe = asyncio.run(c.probe_sources())
    assert set(probe) == {"sdk", "grpc", "pjrt", "workload"}
    assert probe["sdk"]["live"] and "duty×1" in probe["sdk"]["detail"]
    assert not probe["grpc"]["live"]
    assert "8431" in probe["grpc"]["detail"]
    # _FakeDevice.memory_stats() is {} -> PJRT dark, says so.
    assert not probe["pjrt"]["live"]
    assert "memory_stats" in probe["pjrt"]["detail"]
    assert not probe["workload"]["live"]
    assert "workload_dir" in probe["workload"]["detail"]


def test_probe_sources_workload_live(tmp_path):
    from tpumon.collectors.workload import write_report

    c = _collector_with_sdk(None)
    c._client.addr = "x"
    c._client.last_error = "ConnectionRefusedError: refused"
    from tpumon.collectors.workload import WorkloadFileSource

    write_report(str(tmp_path), "job", [{"index": 0, "hbm_used": 5}])
    c._workload = WorkloadFileSource(directory=str(tmp_path))
    probe = asyncio.run(c.probe_sources())
    assert probe["workload"]["live"]
    assert "1 device entry" in probe["workload"]["detail"]
    assert not probe["sdk"]["live"]
    assert "refused" in probe["grpc"]["detail"]
