"""Loadgen model + sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from tpumon.loadgen.model import (  # noqa: E402
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    make_sharded_train_step,
    param_shardings,
)

CFG = ModelConfig(
    vocab=128, d_model=64, n_layers=2, n_heads=8, n_kv_heads=4, d_ff=128, max_seq=32
)


def test_forward_shapes_and_dtype():
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(lambda p, t: forward(CFG, p, t))(params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_remat_matches_plain_forward_and_grads():
    """ModelConfig.remat changes memory scheduling, never math: logits
    and gradients must match the plain forward exactly."""
    import dataclasses
    from functools import partial

    from tpumon.loadgen.model import sgd_train_step

    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    rcfg = dataclasses.replace(CFG, remat=True)
    plain = jax.jit(lambda p, t: forward(CFG, p, t))(params, tokens)
    remat = jax.jit(lambda p, t: forward(rcfg, p, t))(params, tokens)
    assert jnp.array_equal(plain, remat)
    _, loss_plain = jax.jit(partial(sgd_train_step, CFG))(params, tokens)
    _, loss_remat = jax.jit(partial(sgd_train_step, rcfg))(params, tokens)
    assert float(loss_plain) == float(loss_remat)


def test_chunked_attention_matches_naive():
    """attention='chunked' (online-softmax K/V streaming) must reproduce
    the naive path's logits and training step to f32 rounding — incl.
    sequence lengths that don't divide the block."""
    import dataclasses
    from functools import partial

    from tpumon.loadgen.model import sgd_train_step

    cfg = dataclasses.replace(CFG, compute_dtype="float32", max_seq=256)
    ccfg = dataclasses.replace(cfg, attention="chunked", attn_block_k=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 100), 0, cfg.vocab)  # 100 % 32 != 0
    naive = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)
    chunk = jax.jit(lambda p, t: forward(ccfg, p, t))(params, tokens)
    np.testing.assert_allclose(naive, chunk, rtol=2e-5, atol=2e-5)
    _, l1 = jax.jit(partial(sgd_train_step, cfg))(params, tokens)
    _, l2 = jax.jit(partial(sgd_train_step, ccfg))(params, tokens)
    assert abs(float(l1) - float(l2)) < 1e-5
    # T <= block: the chunked config silently uses the naive schedule.
    short = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, cfg.vocab)
    a = jax.jit(lambda p, t: forward(cfg, p, t))(params, short)
    b = jax.jit(lambda p, t: forward(ccfg, p, t))(params, short)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_flash_schedule_matches_naive():
    """attention='flash' (triangle-grid Pallas kernels, fwd AND bwd —
    interpret mode on CPU) reproduces the naive logits AND gradients,
    including T values that don't hit the kernel's 128-row grid
    (internal padding; training T = seq-1 is never aligned)."""
    import dataclasses
    from functools import partial

    from tpumon.loadgen.model import loss_fn, sgd_train_step

    cfg = dataclasses.replace(CFG, compute_dtype="float32", max_seq=256)
    fcfg = dataclasses.replace(cfg, attention="flash", attn_block_k=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    for t in (129, 100):  # aligned-to-128 inputs and unaligned
        tokens = jax.random.randint(
            jax.random.PRNGKey(t), (2, t), 0, cfg.vocab)
        naive = jax.jit(lambda p, tk: forward(cfg, p, tk))(params, tokens)
        flash = jax.jit(lambda p, tk: forward(fcfg, p, tk))(params, tokens)
        np.testing.assert_allclose(naive, flash, rtol=2e-5, atol=2e-5)
        g1 = jax.grad(lambda p: loss_fn(cfg, p, tokens))(params)
        g2 = jax.grad(lambda p: loss_fn(fcfg, p, tokens))(params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)
    _, l1 = jax.jit(partial(sgd_train_step, cfg))(params, tokens)
    _, l2 = jax.jit(partial(sgd_train_step, fcfg))(params, tokens)
    assert abs(float(l1) - float(l2)) < 1e-5


def test_flash_schedule_composes_with_remat():
    """remat + flash: the checkpointed layer body recomputes the kernel
    forward; loss unchanged."""
    import dataclasses
    from functools import partial

    from tpumon.loadgen.model import sgd_train_step

    fcfg = dataclasses.replace(CFG, compute_dtype="float32", max_seq=256,
                               attention="flash")
    rcfg = dataclasses.replace(fcfg, remat=True)
    params = init_params(fcfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, fcfg.vocab)
    _, l1 = jax.jit(partial(sgd_train_step, fcfg))(params, tokens)
    _, l2 = jax.jit(partial(sgd_train_step, rcfg))(params, tokens)
    assert abs(float(l1) - float(l2)) < 1e-6


def test_causality():
    """Changing a future token must not affect earlier logits."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 128)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % 128)
    l1 = forward(CFG, params, t1)
    l2 = forward(CFG, params, t2)
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], rtol=2e-2, atol=2e-2)
    assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-3)


def test_loss_near_uniform_at_init():
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
    loss = float(loss_fn(CFG, params, tokens))
    assert abs(loss - np.log(128)) < 1.0  # ~uniform prediction at init


def test_loss_decreases_with_sgd():
    from tpumon.loadgen.model import sgd_train_step

    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
    step = jax.jit(lambda p, t: sgd_train_step(CFG, p, t, lr=0.5))
    first = None
    for _ in range(10):
        params, loss = step(params, tokens)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def make_mesh(dp=2, tp=4):
    devices = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devices, ("data", "model"))


def test_param_shardings_specs():
    params = init_params(CFG, jax.random.PRNGKey(0))
    mesh = make_mesh()
    sh = param_shardings(mesh, params)
    assert sh["layers"][0]["wq"].spec == P(None, "model")
    assert sh["layers"][0]["wo"].spec == P("model", None)
    assert sh["embed"].spec == P(None, None)


def test_sharded_train_step_8dev():
    """The driver's dryrun path: dp=2 × tp=4 over 8 virtual devices."""
    mesh = make_mesh()
    params = init_params(CFG, jax.random.PRNGKey(0))
    step, placed = make_sharded_train_step(CFG, mesh, params)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128),
        NamedSharding(mesh, P("data", None)),
    )
    new_params, loss = step(placed, tokens)
    jax.block_until_ready(new_params)
    assert np.isfinite(float(loss))
    # Params stay sharded as specified (tp split survives the update).
    wq = new_params["layers"][0]["wq"]
    assert wq.sharding.spec == P(None, "model")


def test_sharded_matches_single_device():
    """SPMD correctness: the sharded step computes the same loss as the
    unsharded reference step."""
    from tpumon.loadgen.model import sgd_train_step

    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
    _, loss_ref = jax.jit(lambda p, t: sgd_train_step(CFG, p, t))(params, tokens)

    mesh = make_mesh()
    step, placed = make_sharded_train_step(CFG, mesh, params)
    _, loss_sharded = step(
        placed, jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    )
    np.testing.assert_allclose(float(loss_ref), float(loss_sharded), rtol=5e-2)


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    g.dryrun_multichip(8)


def test_ici_burn_on_cpu_mesh():
    from tpumon.loadgen.burn import ici_burn

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("ring",))
    out = ici_burn(mesh, mb_per_shift=1, iters=4)
    assert out["devices"] == 4
    assert out["bytes_shifted"] == 4 * 1 * 2**20 * 4
    assert out["gbps"] > 0


# ---------------------------------------------------------------------------
# Slope-measurement integrity guards (BENCH_r02 regression: a paged-
# attention "bandwidth" 1.4x the HBM roofline was published because the
# marginal work sat below the tunnel's noise floor).
# ---------------------------------------------------------------------------


class _FakeClock:
    """Deterministic stand-in device: run(n) 'takes' overhead + n*per_iter
    seconds, with optional per-call noise, without actually sleeping."""

    def __init__(self, per_iter_s: float, overhead_s: float = 0.05,
                 noise: list[float] | None = None):
        self.per_iter_s = per_iter_s
        self.overhead_s = overhead_s
        self.noise = list(noise or [])
        self.now = 0.0
        self.calls: list[int] = []

    def run(self, n: int) -> None:
        self.calls.append(n)
        dt = self.overhead_s + n * self.per_iter_s
        if self.noise:
            dt += self.noise.pop(0)
        self.now += dt


def _patched_guarded_slope(clock, **kw):
    from unittest import mock

    from tpumon.loadgen import burn

    with mock.patch.object(burn.time, "perf_counter", lambda: clock.now):
        return burn._guarded_slope(clock.run, **kw)


def test_guarded_slope_clean_measurement():
    # 10 ms/iter: n=32 -> marginal 96 iters = 0.96 s >= floor; rate exact.
    clock = _FakeClock(per_iter_s=0.01)
    rate, marginal, dt = _patched_guarded_slope(
        clock, iters=32, units_per_iter=100.0, peak_per_sec=None,
        what="t", reps=2)
    assert marginal == 96
    assert abs(dt - 0.96) < 1e-9
    assert abs(rate - 100.0 / 0.01) < 1e-6


def test_guarded_slope_grows_past_noise_floor():
    # 1 ms/iter at n=16: marginal 48 iters = 48 ms < 500 ms floor ->
    # must auto-scale until the marginal clears the floor.
    clock = _FakeClock(per_iter_s=0.001)
    rate, marginal, dt = _patched_guarded_slope(
        clock, iters=16, units_per_iter=1.0, peak_per_sec=None,
        what="t", reps=2)
    assert dt >= 0.5
    assert abs(rate - 1.0 / 0.001) < 1e-6


def test_guarded_slope_rejects_above_roofline():
    # True rate 1000 units/s but peak claims 500: physically impossible,
    # must raise after retries rather than publish.
    import pytest

    clock = _FakeClock(per_iter_s=0.01)
    with pytest.raises(RuntimeError, match="roofline"):
        _patched_guarded_slope(
            clock, iters=32, units_per_iter=10.0, peak_per_sec=500.0,
            what="t", reps=2)


def test_guarded_slope_roofline_retry_recovers():
    # First window poisoned by noise (t(n1) inflated -> slope too small
    # -> rate absurdly high); retries at doubled scale converge to truth.
    clock = _FakeClock(per_iter_s=0.01, noise=[0.0, 0.0, -0.4, 0.0])
    # reps=1: the -0.4 s hiccup lands on the timed n2 rep -> slope 0.56 s
    # (clears the noise floor) -> rate 17,143 > peak; the doubled-scale
    # retry (64 iters) is clean and lands below peak.
    rate, marginal, dt = _patched_guarded_slope(
        clock, iters=32, units_per_iter=100.0, peak_per_sec=12_000.0,
        what="t", reps=1)
    assert abs(rate - 10_000.0) < 1e-6
    assert marginal == 192


def test_measure_rooflines_table():
    from tpumon.loadgen.burn import device_rooflines

    peaks = device_rooflines()
    # On the CPU test platform every peak is unknown -> guards disengage.
    assert set(peaks) == {"bf16_tflops", "int8_tops", "hbm_gbps"}
    for v in peaks.values():
        assert v is None or v > 0


def test_measure_paged_engine_step_both_paths():
    """measure_paged_engine_step_ms (the bench's gather-vs-kernel
    engine-step settlement) runs both read paths on the CPU test shape
    and returns coherent positive numbers."""
    import dataclasses

    from tpumon.loadgen.burn import measure_paged_engine_step_ms
    from tpumon.loadgen.serving import ServeConfig

    cfg = ServeConfig(
        model=dataclasses.replace(
            CFG, compute_dtype="float32", max_seq=64),
        slots=2, prefill_len=8, kv_layout="paged")
    for pa in ("gather", "kernel"):
        out = measure_paged_engine_step_ms(
            dataclasses.replace(cfg, paged_attn=pa), inner_steps=256)
        assert out["ms_per_step"] > 0 and out["kv_gbps_floor"] > 0
        assert out["paged_attn"] == pa


def test_flash_schedule_under_dp_tp_mesh():
    """attention='flash' composes with the dp x tp sharded trainer:
    the pallas calls compile under pjit and the loss matches the
    single-device flash path exactly. (XLA may replicate around the
    kernel — the fold mixes batch and head dims — so the multi-chip
    rec stays chunked/sp; this pins correctness, not efficiency.)"""
    import dataclasses

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpumon.loadgen.model import loss_fn, make_sharded_train_step

    devs = jax.devices()
    if len(devs) < 8:
        import pytest

        pytest.skip("needs the 8-device CPU mesh")
    cfg = dataclasses.replace(
        CFG, compute_dtype="float32", max_seq=256, attention="flash")
    mesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("data", "model"))
    params = init_params(cfg, jax.random.PRNGKey(0))
    step, placed = make_sharded_train_step(cfg, mesh, params)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0, cfg.vocab),
        NamedSharding(mesh, P("data", None)))
    _, loss = step(placed, tokens)
    ref = loss_fn(cfg, params, tokens)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
