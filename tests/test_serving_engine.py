"""Serving-engine tests: KV-cache decode correctness vs the full forward
pass, continuous-batching lifecycle, and the /metrics exposition being
scrapeable by tpumon's own serving collector (the in-tree north-star
loop, BASELINE config 4)."""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from tpumon.collectors.serving import ServingCollector, distill_serving_metrics
from tpumon.loadgen.model import ModelConfig, forward, init_params
from tpumon.loadgen.serving import (
    ServeConfig,
    ServingEngine,
    decode_step,
    init_cache,
    prefill,
    start_metrics_server,
)

# float32 so incremental (KV-cached) and full-recompute paths agree to
# fp-roundoff rather than bf16 rounding.
CFG = ServeConfig(
    model=ModelConfig(vocab=97, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq=32,
                      compute_dtype="float32"),
    slots=2, prefill_len=8,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG.model, jax.random.PRNGKey(7))


def test_prefill_logits_match_forward(params):
    prompt = [3, 11, 42, 7, 29]
    n = len(prompt)
    toks = jnp.asarray(prompt + [0] * (CFG.prefill_len - n), jnp.int32)
    cache = init_cache(CFG)
    cache, logits = prefill(CFG, params, cache, toks, jnp.int32(n),
                            jnp.int32(0))
    full = forward(CFG.model, params, jnp.asarray([prompt], jnp.int32))
    assert jnp.allclose(logits, full[0, -1], atol=2e-4), (
        "prefill last-position logits must equal full forward")


def test_decode_steps_match_forward(params):
    """Greedy generation through the KV cache must reproduce the
    recompute-everything reference token-for-token."""
    prompt = [5, 1, 88, 14]
    n = len(prompt)
    toks = jnp.asarray(prompt + [0] * (CFG.prefill_len - n), jnp.int32)
    cache = init_cache(CFG)
    slot = 1  # non-zero slot: exercises the per-slot cache offsets
    cache, logits = prefill(CFG, params, cache, toks, jnp.int32(n),
                            jnp.int32(slot))
    seq = list(prompt) + [int(jnp.argmax(logits))]
    positions = jnp.zeros((CFG.slots,), jnp.int32).at[slot].set(n)
    last = jnp.zeros((CFG.slots,), jnp.int32).at[slot].set(seq[-1])
    for _ in range(6):
        cache, step_logits = decode_step(CFG, params, cache, last, positions)
        full = forward(CFG.model, params, jnp.asarray([seq], jnp.int32))
        assert jnp.allclose(step_logits[slot], full[0, -1], atol=2e-4)
        nxt = int(jnp.argmax(step_logits[slot]))
        assert nxt == int(jnp.argmax(full[0, -1]))
        seq.append(nxt)
        positions = positions.at[slot].add(1)
        last = last.at[slot].set(nxt)


def test_engine_completes_requests_and_counts():
    eng = ServingEngine(cfg=CFG)
    reqs = [eng.submit([i + 1, i + 2, i + 3], max_new=5) for i in range(5)]
    eng.drain()
    for r in reqs:
        assert r.done.is_set()
        assert len(r.output) == 6  # first token + 5 decode tokens
        assert r.ttft_s is not None and r.ttft_s >= 0
    assert eng.completed_total == 5
    assert eng.requests_total == 5
    assert eng.tokens_total == sum(len(r.output) for r in reqs)


def test_queue_overflows_slots_then_drains():
    eng = ServingEngine(cfg=CFG)
    reqs = [eng.submit([1, 2], max_new=3) for _ in range(CFG.slots * 3)]
    eng.step()
    # more requests than slots: some must be queued, and the gauge says so
    d = distill_serving_metrics(eng.metrics_text())
    assert d["queue_depth"] >= 1
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    d = distill_serving_metrics(eng.metrics_text())
    assert d["queue_depth"] == 0


def test_max_new_zero_gets_exactly_one_token():
    eng = ServingEngine(cfg=CFG)
    req = eng.submit([1, 2, 3], max_new=0)
    eng.drain()
    assert req.done.is_set()
    assert len(req.output) == 1  # the prefill token only, no decode


def test_queue_backpressure_rejects():
    eng = ServingEngine(cfg=CFG, max_queue=3)
    accepted = [eng.submit([1], max_new=2) for _ in range(3)]
    dropped = eng.submit([1], max_new=2)
    assert dropped.done.is_set() and dropped.output == []
    assert eng.rejected_total == 1
    assert eng.requests_total == 3
    eng.drain()
    assert all(r.done.is_set() for r in accepted)


def test_metrics_exposition_distills():
    eng = ServingEngine(cfg=CFG)
    eng.submit([4, 5, 6], max_new=4)
    eng.drain()
    text = eng.metrics_text()
    d = distill_serving_metrics(text)
    assert d["tokens_total"] == eng.tokens_total
    assert d["requests_total"] == 1
    assert "ttft_p50_ms" in d, "TTFT histogram must yield a quantile"
    assert d["ttft_p50_ms"] > 0


def test_collector_scrapes_live_engine():
    eng = ServingEngine(cfg=CFG)
    eng.submit([9, 8, 7], max_new=4)
    eng.drain()
    server, port = start_metrics_server(eng, port=0)
    try:
        col = ServingCollector(targets=(f"http://127.0.0.1:{port}/metrics",))
        s1 = asyncio.run(col.collect())
        assert s1.ok, s1.error
        eng.submit([1, 2, 3], max_new=4)
        eng.drain()
        s2 = asyncio.run(col.collect())
        t = s2.data[0]
        assert t["ok"]
        assert t["tokens_total"] == eng.tokens_total
        assert t["tokens_per_sec"] >= 0  # rate from the counter delta
        assert "ttft_p50_ms" in t
    finally:
        server.shutdown()
        server.server_close()


def test_chunked_prefill_matches_forward(params):
    """A prompt longer than prefill_len runs as multiple fixed-shape
    chunks; the final logits must equal the full forward pass at the last
    position (chunk queries attend prior chunks through the cache)."""
    prompt = [(7 * i + 3) % CFG.model.vocab for i in range(19)]  # 19 > 2*8
    cache = init_cache(CFG)
    p = CFG.prefill_len
    for c0 in range(0, len(prompt), p):
        chunk = prompt[c0:c0 + p]
        toks = jnp.asarray(chunk + [0] * (p - len(chunk)), jnp.int32)
        cache, logits = prefill(CFG, params, cache, toks,
                                jnp.int32(len(chunk)), jnp.int32(1),
                                jnp.int32(c0))
    full = forward(CFG.model, params, jnp.asarray([prompt], jnp.int32))
    assert jnp.allclose(logits, full[0, -1], atol=2e-4)


def test_engine_long_prompt_decodes_correctly():
    """End to end: a 20-token prompt (prefill_len=8) admits via chunked
    prefill and then greedy-decodes the same stream as the
    recompute-everything reference."""
    eng = ServingEngine(cfg=CFG)
    prompt = [(5 * i + 2) % CFG.model.vocab for i in range(20)]
    r = eng.submit(prompt, max_new=5)
    eng.drain()
    assert len(r.output) == 6
    params = eng.params
    seq = list(prompt)
    for _ in range(6):
        full = forward(CFG.model, params, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(full[0, -1])))
    assert r.output == seq[len(prompt):]


def test_prompt_over_max_seq_refused():
    # Truncating would silently serve a DIFFERENT prompt; the refusal
    # is exactly the admission boundary ring mode moves (ServeConfig
    # .ring_stripes — the ring admission test in test_scheduler.py).
    eng = ServingEngine(cfg=CFG)
    r = eng.submit(list(range(100)), max_new=2)  # 100 > max_seq=32
    assert r.done.is_set()
    assert r.status == "rejected"
    assert r.output == []
    # In-cap prompts are untouched by the refusal boundary.
    ok = eng.submit(list(range(CFG.model.max_seq - 1)), max_new=0)
    eng.drain()
    assert ok.status == "completed"


def test_engine_lifecycle_fuzz():
    """Random submit/step interleavings: every request terminates, slots
    never leak, token accounting stays consistent — the invariants that
    continuous batching must keep under churn."""
    import random

    rng = random.Random(42)
    eng = ServingEngine(cfg=CFG, max_queue=8)
    reqs = []
    for _ in range(120):
        action = rng.random()
        if action < 0.4:
            n = rng.randint(1, CFG.model.max_seq + 10)  # incl. over-length
            reqs.append(eng.submit(
                [rng.randrange(CFG.model.vocab) for _ in range(n)],
                max_new=rng.randint(0, 6),
                temperature=rng.choice([0.0, 0.0, 1.0]),
                top_k=rng.choice([0, 4]),
            ))
        else:
            eng.step()
    eng.drain()
    assert all(r.done.is_set() for r in reqs)
    served = [r for r in reqs if r.output]
    rejected = [r for r in reqs if not r.output]
    assert len(served) + len(rejected) == len(reqs)
    assert eng.completed_total == len(served)
    assert eng.rejected_total == len(rejected)
    assert eng.tokens_total == sum(len(r.output) for r in served)
    assert all(s is None for s in eng._slots)  # no leaked slots
    for r in served:
        assert all(0 <= t < CFG.model.vocab for t in r.output)
        assert len(r.output) <= r.max_new + 1


def test_start_background_engine_option_passthrough():
    """--serve-loadgen's engine options reach the engine: spec/paged
    configs built from the default model; bad combos raise (app surfaces
    them as usage errors)."""
    import pytest

    from tpumon.loadgen.serving import start_background

    engine, url, stop = start_background(
        rps=0.0, spec_len=2, prefix_cache=4)
    try:
        assert engine.spec_len == 2
        assert engine.prefix_cache is not None
        assert url.endswith("/metrics")
    finally:
        stop.set()

    engine2, _, stop2 = start_background(
        rps=0.0, kv_layout="paged", pool_pages=9)
    try:
        assert engine2.paged and engine2.allocator.num_pages == 9
    finally:
        stop2.set()

    # paged + spec compose since r04 (paged_kv.paged_decode_block).
    engine3, _, stop3 = start_background(
        rps=0.0, kv_layout="paged", spec_len=2)
    try:
        assert engine3.paged and engine3.spec_len == 2
    finally:
        stop3.set()
    # int8 KV + spec remains rejected.
    with pytest.raises(ValueError):
        start_background(rps=0.0, kv_dtype="int8", spec_len=2)


def test_pool_pages_requires_paged_layout():
    import pytest

    from tpumon.loadgen.serving import ServeConfig, ServingEngine

    with pytest.raises(ValueError, match="pool_pages"):
        ServingEngine(cfg=ServeConfig(pool_pages=9))


def test_start_background_ckpt_adopts_saved_architecture(tmp_path):
    """Engine options combined with a checkpoint must serve the
    checkpoint's architecture, not silently fall back to the demo
    default."""
    from tpumon.loadgen.checkpoint import saved_model_config
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import start_background
    from tpumon.loadgen.train import TrainConfig, run_train

    cfg = TrainConfig(
        model=ModelConfig(vocab=64, d_model=32, n_layers=1, n_heads=2,
                          n_kv_heads=1, d_ff=64, max_seq=32),
        steps=2, batch=2, seq=8, ckpt_dir=str(tmp_path), ckpt_every=1)
    run_train(cfg, log=lambda *a: None)
    assert saved_model_config(str(tmp_path)) is not None

    engine, _, stop = start_background(
        rps=0.0, ckpt_dir=str(tmp_path), spec_len=2)
    try:
        assert engine.cfg.model.vocab == 64  # saved arch, not demo 512
        assert engine.spec_len == 2
        assert engine.ckpt_step is not None  # weights actually restored
    finally:
        stop.set()
