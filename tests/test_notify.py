"""Alert webhook notification sinks (tpumon.notify).

The reference delivers alerts nowhere — they exist only while a browser
polls /api/alerts (monitor_server.js:282-288). These tests pin tpumon's
push path: fired/resolved timeline events reach webhook sinks exactly
once, Slack sinks get message-shaped payloads, severity filtering works,
and sink failures are counted instead of raised.
"""

from __future__ import annotations

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpumon.alerts import AlertEngine
from tpumon.app import build
from tpumon.config import load_config
from tpumon.notify import WebhookNotifier, slack_text
from tpumon.sampler import Sampler


class WebhookReceiver:
    """In-process HTTP sink capturing POSTed JSON bodies."""

    def __init__(self, status: int = 200):
        self.bodies: list[dict] = []
        received = self.bodies

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(status)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_port}/hook"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def fired(key="host.cpu.critical", severity="critical", seq=1):
    return {
        "seq": seq,
        "ts": 0.0,
        "state": "fired",
        "severity": severity,
        "title": "CPU usage critical",
        "desc": "CPU at 97.0%",
        "fix": "scale out",
        "key": key,
    }


def run(coro):
    return asyncio.run(coro)


def test_generic_sink_receives_event_batch():
    rx = WebhookReceiver()
    try:

        async def go():
            n = WebhookNotifier(urls=(rx.url,))
            n.notify([fired()])
            await n.close()

        run(go())
        assert len(rx.bodies) == 1
        body = rx.bodies[0]
        assert body["source"] == "tpumon"
        assert body["events"][0]["key"] == "host.cpu.critical"
        assert body["events"][0]["state"] == "fired"
    finally:
        rx.close()


def test_slack_sink_gets_text_payload():
    rx = WebhookReceiver()
    try:

        async def go():
            n = WebhookNotifier(urls=("slack+" + rx.url,))
            n.notify([fired(), {**fired(seq=2), "state": "resolved"}])
            await n.close()

        run(go())
        assert len(rx.bodies) == 1
        text = rx.bodies[0]["text"]
        assert "CPU usage critical" in text
        assert "resolved" in text
        assert "events" not in rx.bodies[0]
    finally:
        rx.close()


def test_min_severity_filters_fires_but_not_resolves():
    rx = WebhookReceiver()
    try:

        async def go():
            n = WebhookNotifier(urls=(rx.url,), min_severity="critical")
            n.notify([fired(severity="minor", key="host.cpu.minor")])
            n.notify(
                [{**fired(severity="minor", seq=2), "state": "resolved"}]
            )
            await n.close()

        run(go())
        # Minor fire suppressed; the resolve still went out.
        assert len(rx.bodies) == 1
        assert rx.bodies[0]["events"][0]["state"] == "resolved"
    finally:
        rx.close()


def test_sink_failure_counted_not_raised():
    async def go():
        n = WebhookNotifier(urls=("http://127.0.0.1:9/unroutable",), timeout_s=0.5)
        n.notify([fired()])
        await n.close()
        return n

    n = run(go())
    assert n.sinks[0].failures == 1
    assert n.sinks[0].last_error
    assert "unroutable" not in (n.sinks[0].last_error or "")  # sanity: message is the exception


def test_sampler_dispatches_each_event_once():
    rx = WebhookReceiver()
    try:
        cfg = load_config(
            env={
                "TPUMON_ACCEL_BACKEND": "fake:v5e-8",
                "TPUMON_K8S_MODE": "none",
                "TPUMON_COLLECTORS": "host,accel",
                "TPUMON_PORT": "0",
                "TPUMON_ALERT_WEBHOOKS": rx.url,
            }
        )
        sampler, _ = build(cfg)
        assert isinstance(sampler.notifier, WebhookNotifier)

        async def go():
            # Drive the engine directly (deterministic) through the
            # sampler's dispatch path.
            sampler.engine.evaluate(host={"cpu": {"percent": 97.0}})
            sampler._notify_new_events()
            sampler._notify_new_events()  # no new events => no second POST
            sampler.engine.evaluate(host={"cpu": {"percent": 97.0}})
            sampler._notify_new_events()  # still-active alert => no event
            await sampler.notifier.close()

        run(go())
        assert len(rx.bodies) == 1
        keys = [e["key"] for e in rx.bodies[0]["events"]]
        assert "host.cpu.critical" in keys
    finally:
        rx.close()


def test_restored_events_not_repaged():
    engine = AlertEngine()
    engine.evaluate(host={"cpu": {"percent": 97.0}})
    state = engine.to_state()

    cfg = load_config(
        env={
            "TPUMON_ACCEL_BACKEND": "none",
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host",
            "TPUMON_PORT": "0",
        }
    )
    sampler, _ = build(cfg)
    sampler.engine.load_state(state)
    sampler.mark_events_notified()
    rxed: list = []
    sampler.notifier = type(
        "N", (), {"notify": lambda self, ev: rxed.append(ev)}
    )()
    sampler._notify_new_events()
    assert rxed == []
    # But a genuinely new event after restore still dispatches.
    sampler.engine.evaluate(host={"memory": {"percent": 97.0}})
    sampler._notify_new_events()
    assert len(rxed) == 1


def test_slack_text_formats_fix_line():
    text = slack_text([fired()], hostname="host-a")
    assert "host-a" in text and "fix: scale out" in text
