"""tpumon benchmark: scrape→render p50 + perf-claim regression metrics.

Driver metric (BASELINE.json): "per-chip MXU%+HBM% scrape→render p50
latency; exporter samples/sec". One measured cycle is:

    trigger a fresh accel+host sample (sampler.tick_fast)
      → HTTP GET /api/accel/metrics against the live server
      → JSON parsed (the dashboard's render input)

i.e. the full data path a dashboard poll exercises, with collection
*included* (the reference collects synchronously inside the request —
execSync per hit, monitor_server.js:83-95 — so this is the comparable
unit of work).

vs_baseline: the reference publishes no latency numbers (BASELINE.md);
its effective scrape→render freshness is bounded by its 5 s realtime
polling interval (monitor.html:605, the reference's own headline
operational parameter). vs_baseline is therefore reported as
5000 ms / measured p50 — how many times fresher tpumon's pipeline is
than the reference's refresh cadence.

Beyond the headline, every perf claim PARITY.md makes is re-measured
here so a regression in any kernel or loop shows up in the next
BENCH_r{N}.json (VERDICT round-1 item #2):

  int8_matmul_*        quant_matmul Pallas kernel vs XLA's fused dequant
  paged_attention_*    paged-decode KV streaming vs XLA fused gather
  train_*              sharded trainer MFU % + tokens/s
  serving_*            in-tree engine end-to-end tokens/s
  federation_*         merged scrape→render p50 + exporter render time
                       for a simulated 8-host × 8-chip (64-chip) fleet

Kernel numbers need the real MXU and are null off-TPU; the rest run
anywhere (small shapes off-TPU). Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import statistics
import sys
import threading
import time
import urllib.request


def _p50(xs: list[float]) -> float:
    return statistics.median(xs)


def _start_burn(stop: threading.Event) -> threading.Thread | None:
    """Background MXU load so scrape latency is measured under load."""

    def run():
        try:
            import jax

            from tpumon.loadgen.burn import mxu_burn

            size = 2048 if jax.devices()[0].platform == "tpu" else 128
            while not stop.is_set():
                mxu_burn(seconds=0.5, size=size, iters=8)
        except Exception:
            pass  # benching without load is still valid

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _detect_backend() -> str:
    """'jax' when a real TPU is visible, else the fake topology. Probed in
    a subprocess with a hard timeout because a wedged device runtime
    hangs jax.devices() forever — bench must not hang with it."""
    try:
        import subprocess

        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=90,
        )
        if probe.returncode == 0 and probe.stdout.strip() == "tpu":
            return "jax"
    except Exception:
        pass
    return "fake:v5e-8"


async def _bench_scrape(backend: str, iters: int = 50, warmup: int = 5) -> dict:
    """Headline: scrape→render p50 against the live server."""
    from tpumon.app import build
    from tpumon.config import load_config

    cfg = load_config(
        env={
            "TPUMON_PORT": "0",
            "TPUMON_HOST": "127.0.0.1",
            "TPUMON_ACCEL_BACKEND": backend,
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host,accel",
        }
    )
    sampler, server = build(cfg)
    await sampler.tick_all()
    await server.start()
    url = f"http://127.0.0.1:{server.port}/api/accel/metrics"

    def fetch() -> dict:
        with urllib.request.urlopen(url) as r:
            return json.loads(r.read())

    stop = threading.Event()
    if backend == "jax":  # fake counters are synthetic; no point burning
        _start_burn(stop)
    try:
        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await sampler.tick_fast()  # scrape: fresh device counters
            data = await asyncio.to_thread(fetch)  # render: HTTP + JSON
            dt = (time.perf_counter() - t0) * 1e3
            assert "chips" in data
            if i >= warmup:
                cycle_ms.append(dt)

        # Sampler-only rate (exporter samples/sec): how fast the device
        # counter loop can run, excluding HTTP.
        t0 = time.perf_counter()
        n = 50
        for _ in range(n):
            await sampler.tick_fast()
        samples_per_sec = n / (time.perf_counter() - t0)
    finally:
        stop.set()
        await server.stop()

    p50 = _p50(cycle_ms)
    return {
        "metric": "accel_scrape_to_render_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(5000.0 / p50, 1),
        "p95_ms": round(sorted(cycle_ms)[int(0.95 * len(cycle_ms)) - 1], 3),
        "sampler_samples_per_sec": round(samples_per_sec, 1),
        "chips": len(sampler.chips()),
        "accel_backend": backend,
    }


def _bench_kernels() -> dict:
    """PARITY kernel claims, re-measured: int8 matmul (Pallas vs XLA's
    fused dequant) and paged-attention decode (Pallas vs fused gather).
    Slope-timed (loadgen.burn.measure_*) so remote-dispatch overhead
    cancels. Real-MXU-only — interpret-mode numbers would be noise."""
    from tpumon.loadgen.burn import (
        measure_int8_tflops,
        measure_mxu_tflops,
        measure_paged_gbps,
    )

    mm_pallas = measure_mxu_tflops(use_pallas=True)
    mm_xla = measure_mxu_tflops(use_pallas=False)
    i8_pallas = measure_int8_tflops(use_pallas=True)
    i8_xla = measure_int8_tflops(use_pallas=False)
    pa_pallas = measure_paged_gbps(use_pallas=True)
    pa_xla = measure_paged_gbps(use_pallas=False)
    return {
        "mxu_matmul_pallas_tflops": round(mm_pallas["tflops"], 2),
        "mxu_matmul_xla_tflops": round(mm_xla["tflops"], 2),
        "mxu_matmul_vs_xla": round(mm_pallas["tflops"] / mm_xla["tflops"], 2),
        "int8_matmul_pallas_tflops": round(i8_pallas["tflops"], 2),
        "int8_matmul_xla_tflops": round(i8_xla["tflops"], 2),
        "int8_matmul_vs_xla": round(i8_pallas["tflops"] / i8_xla["tflops"], 2),
        "paged_attention_pallas_kv_gbps": round(pa_pallas["kv_gbps"], 1),
        "paged_attention_xla_kv_gbps": round(pa_xla["kv_gbps"], 1),
        "paged_attention_vs_xla": round(
            pa_pallas["kv_gbps"] / pa_xla["kv_gbps"], 2
        ),
    }


def _bench_train(on_tpu: bool) -> dict:
    """Trainer MFU (achieved model FLOP/s over device peak) + tokens/s,
    measured with the whole step loop fused into one jitted scan
    (loadgen.train.fused_train_bench) so the number reflects device
    throughput, not Python dispatch or tunnel RTT. Off-TPU shapes shrink
    to keep CI fast (MFU is null there — no known peak for CPU)."""
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig, fused_train_bench

    if on_tpu:
        # d2048/L6: the best-MFU shape that fits a 16 GiB v5e without
        # remat (bigger models train via ModelConfig.remat — measured
        # d2048/L12 at ~43% MFU — but the headline tracks the peak).
        model = ModelConfig(
            vocab=4096, d_model=2048, n_layers=6, n_heads=16, n_kv_heads=16,
            d_ff=8192, max_seq=1024,
        )
        cfg = TrainConfig(model=model, batch=8, seq=1024)
        steps = 16
    else:
        model = ModelConfig()
        cfg = TrainConfig(model=model, batch=2, seq=64)
        steps = 4
    out = fused_train_bench(cfg, steps=steps)
    return {
        "train_mfu_pct": round(out["mfu_pct"], 2)
        if out["mfu_pct"] is not None
        else None,
        "train_tokens_per_sec": round(out["tokens_per_sec"], 1),
    }


def _bench_serving(on_tpu: bool) -> dict:
    """End-to-end engine throughput: continuous batching, KV-cached
    decode, greedy sampling. Tokens/s = generated tokens / wall time
    including prefill (the serving-loop number PARITY claims)."""
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig, ServingEngine

    if on_tpu:
        cfg = ServeConfig(
            model=ModelConfig(vocab=4096, d_model=512, n_layers=4,
                              n_heads=8, n_kv_heads=8, d_ff=2048,
                              max_seq=512),
            slots=8, prefill_len=32,
        )
        n_req, max_new = 24, 64
    else:
        cfg = None  # tiny default model
        n_req, max_new = 8, 16
    prompt = list(range(1, 17))

    def run(block: int) -> float:
        import dataclasses

        c = cfg
        if c is not None:
            c = dataclasses.replace(c, decode_block=block)
        elif block > 1:
            from tpumon.loadgen.serving import default_engine_config

            c = dataclasses.replace(default_engine_config(),
                                    decode_block=block)
        engine = ServingEngine(c)
        # Warmup: compile prefill + decode out of the measured window.
        engine.submit(prompt, max_new=2)
        engine.drain()
        t0 = time.perf_counter()
        reqs = [engine.submit(prompt, max_new=max_new) for _ in range(n_req)]
        engine.drain()
        return sum(len(r.output) for r in reqs) / (time.perf_counter() - t0)

    return {
        "serving_tokens_per_sec": round(run(1), 1),
        # Fused plain decode (ServeConfig.decode_block): 8 steps per
        # dispatch — the engine's dispatch-overhead amortization.
        "serving_block8_tokens_per_sec": round(run(8), 1),
        "serving_requests": n_req,
    }


async def _bench_federation(
    n_peers: int = 8, iters: int = 40, warmup: int = 5
) -> dict:
    """Monitor-at-scale: one aggregator federating n_peers in-process
    tpumon instances, each serving a fake v5e-8 host (n_peers×8 chips —
    a v5p-64-style fleet). Reports the merged scrape→render p50 through
    the aggregator's live HTTP server and the exporter render time at
    that chip count (VERDICT round-1 item #7)."""
    from tpumon.app import build
    from tpumon.collectors.accel_peers import PeerFederatedCollector
    from tpumon.config import load_config
    from tpumon.exporter import render_exporter

    peers = []
    try:
        urls = []
        for i in range(n_peers):
            cfg = load_config(
                env={
                    "TPUMON_PORT": "0",
                    "TPUMON_HOST": "127.0.0.1",
                    "TPUMON_ACCEL_BACKEND": f"fake:v5e-8@fleet{i}",
                    "TPUMON_K8S_MODE": "none",
                    "TPUMON_COLLECTORS": "accel",
                }
            )
            sampler, server = build(cfg)
            await sampler.tick_fast()
            await server.start()
            peers.append((sampler, server))
            urls.append(f"127.0.0.1:{server.port}")

        agg_cfg = load_config(
            env={
                "TPUMON_PORT": "0",
                "TPUMON_HOST": "127.0.0.1",
                "TPUMON_ACCEL_BACKEND": "none",
                "TPUMON_K8S_MODE": "none",
                "TPUMON_COLLECTORS": "accel",
                "TPUMON_PEERS": ",".join(urls),
            }
        )
        agg_sampler, agg_server = build(agg_cfg)
        assert isinstance(agg_sampler.accel, PeerFederatedCollector)
        await agg_sampler.tick_fast()
        await agg_server.start()
        peers.append((agg_sampler, agg_server))
        url = f"http://127.0.0.1:{agg_server.port}/api/accel/metrics"

        def fetch() -> dict:
            with urllib.request.urlopen(url) as r:
                return json.loads(r.read())

        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await agg_sampler.tick_fast()
            data = await asyncio.to_thread(fetch)
            dt = (time.perf_counter() - t0) * 1e3
            if i >= warmup:
                cycle_ms.append(dt)
        n_chips = len(data["chips"])

        render_ms: list[float] = []
        for _ in range(20):
            t0 = time.perf_counter()
            text = render_exporter(agg_sampler)
            render_ms.append((time.perf_counter() - t0) * 1e3)
        assert "tpu_mxu_duty_cycle_pct" in text
    finally:
        for sampler, server in peers:
            with contextlib.suppress(Exception):
                await server.stop()

    return {
        "federation_chips": n_chips,
        "federation_scrape_to_render_p50_ms": round(_p50(cycle_ms), 3),
        "federation_exporter_render_ms": round(_p50(render_ms), 3),
    }


def _note(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}", file=sys.stderr)


_T0 = time.perf_counter()

# Each phase runs in its own subprocess (device/compile state fully
# isolated; a wedged phase times out to nulls instead of hanging the
# driver). name -> (timeout_s, null-result keys).
PHASES: dict[str, tuple[float, tuple[str, ...]]] = {
    "scrape": (300, ("metric", "value", "unit", "vs_baseline")),
    "federation": (120, ("federation_chips",
                         "federation_scrape_to_render_p50_ms",
                         "federation_exporter_render_ms")),
    "kernels": (480, ("mxu_matmul_pallas_tflops", "mxu_matmul_xla_tflops",
                      "mxu_matmul_vs_xla",
                      "int8_matmul_pallas_tflops", "int8_matmul_xla_tflops",
                      "int8_matmul_vs_xla", "paged_attention_pallas_kv_gbps",
                      "paged_attention_xla_kv_gbps", "paged_attention_vs_xla")),
    "train": (420, ("train_mfu_pct", "train_tokens_per_sec")),
    "serving": (700, ("serving_tokens_per_sec",
                      "serving_block8_tokens_per_sec", "serving_requests")),
}


def _run_phase(name: str, backend: str) -> dict:
    on_tpu = backend == "jax"
    if name == "scrape":
        return asyncio.run(_bench_scrape(backend))
    if name == "federation":
        return asyncio.run(_bench_federation())
    if name == "kernels":
        if not on_tpu:
            # Keep the documented key set stable off-TPU: explicit nulls,
            # not silently-absent keys.
            return {k: None for k in PHASES["kernels"][1]}
        return _bench_kernels()
    if name == "train":
        return _bench_train(on_tpu)
    if name == "serving":
        return _bench_serving(on_tpu)
    raise ValueError(f"unknown phase {name!r}")


def main(argv: list[str] | None = None) -> int:
    import subprocess

    argv = sys.argv[1:] if argv is None else argv
    if "--phase" in argv:
        # Child mode: run one phase, print its JSON fragment.
        name = argv[argv.index("--phase") + 1]
        backend = argv[argv.index("--backend") + 1]
        print(json.dumps(_run_phase(name, backend)))
        return 0

    backend = _detect_backend()
    _note(f"backend={backend}")
    result: dict = {}
    for name, (timeout_s, null_keys) in PHASES.items():
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--phase", name,
                 "--backend", backend],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr.strip()[-500:])
            result.update(json.loads(proc.stdout.strip().splitlines()[-1]))
            _note(f"{name} done")
        except Exception as e:
            _note(f"{name} FAILED: {type(e).__name__}: {str(e)[:200]}")
            for k in null_keys:
                result.setdefault(k, None)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
