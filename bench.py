"""tpumon benchmark: scrape→render p50 + perf-claim regression metrics.

Driver metric (BASELINE.json): "per-chip MXU%+HBM% scrape→render p50
latency; exporter samples/sec". One measured cycle is:

    trigger a fresh accel+host sample (sampler.tick_fast)
      → HTTP GET /api/accel/metrics against the live server
      → JSON parsed (the dashboard's render input)

i.e. the full data path a dashboard poll exercises, with collection
*included* (the reference collects synchronously inside the request —
execSync per hit, monitor_server.js:83-95 — so this is the comparable
unit of work).

vs_baseline: the reference publishes no latency numbers (BASELINE.md);
its effective scrape→render freshness is bounded by its 5 s realtime
polling interval (monitor.html:605, the reference's own headline
operational parameter). vs_baseline is therefore reported as
5000 ms / measured p50 — how many times fresher tpumon's pipeline is
than the reference's refresh cadence.

Beyond the headline, every perf claim PARITY.md makes is re-measured
here so a regression in any kernel or loop shows up in the next
BENCH_r{N}.json (VERDICT round-1 item #2):

  int8_matmul_*        quant_matmul Pallas kernel vs XLA's fused dequant
  paged_attention_*    paged-decode KV streaming vs XLA fused gather
  train_*              sharded trainer MFU % + tokens/s
  serving_*            in-tree engine end-to-end tokens/s
  fastpath_* / sse_*   epoch-cached render + delta-SSE wire costs at 64
                       and 256 fake chips (docs/perf.md)
  events_* / anomaly_* journal append p50 and EWMA-detector tick
                       overhead at v5p-64 (docs/events.md)
  history_*            columnar history engine: record/query p50,
                       resident bytes/point vs the tuple-deque layout,
                       binary snapshot write/restore, per-chip
                       recording at v5p-256 (docs/perf.md)
  federation_*         merged scrape→render p50 + exporter render time
                       for a simulated 8-host × 8-chip (64-chip) fleet
                       and a 4-peer × v5p-64 (256-chip) fleet

Kernel numbers need the real MXU and are null off-TPU; the rest run
anywhere (small shapes off-TPU).

Artifact pipeline (VERDICT r05 weak #1: the full JSON outgrew the
driver's 2000-char stdout tail and r05's number-of-record committed as
``parsed: null``): the FULL result — every key, including the nested
diagnostic dicts — is written to a results file (``--out``, default
BENCH_FULL.json), and stdout's final line is a compact keys-of-record
summary (KEYS_OF_RECORD, scalars only, < 1800 bytes — pinned by
tests/test_bench_artifact.py) that points at the file. Truncating the
tail can no longer lose the record.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import statistics
import sys
import threading
import time
import urllib.request


def _p50(xs: list[float]) -> float:
    return statistics.median(xs)


def _start_burn(stop: threading.Event) -> threading.Thread | None:
    """Background MXU load so scrape latency is measured under load."""

    def run():
        try:
            import jax

            from tpumon.loadgen.burn import mxu_burn

            size = 2048 if jax.devices()[0].platform == "tpu" else 128
            while not stop.is_set():
                mxu_burn(seconds=0.5, size=size, iters=8)
        except Exception:
            pass  # benching without load is still valid

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _detect_backend() -> str:
    """'jax' when a real TPU is visible, else the fake topology. Probed in
    a subprocess with a hard timeout because a wedged device runtime
    hangs jax.devices() forever — bench must not hang with it."""
    try:
        import subprocess

        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=90,
        )
        if probe.returncode == 0 and probe.stdout.strip() == "tpu":
            return "jax"
    except Exception:
        pass
    return "fake:v5e-8"


async def _serve_bench_app(backend: str, **extra_env):
    """Shared bench bring-up: one host+accel instance over ``backend``,
    primed and listening. Returns (sampler, server, fetch) where
    ``fetch()`` GETs /api/accel/metrics — the dashboard's render input.
    Every phase that measures the live server goes through here so the
    harness can't drift between phases (e.g. the observability phase's
    on/off comparison must differ ONLY in TPUMON_TRACE_RING)."""
    from tpumon.app import build
    from tpumon.config import load_config

    cfg = load_config(
        env={
            "TPUMON_PORT": "0",
            "TPUMON_HOST": "127.0.0.1",
            "TPUMON_ACCEL_BACKEND": backend,
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host,accel",
            **extra_env,
        }
    )
    sampler, server = build(cfg)
    await sampler.tick_all()
    await server.start()
    url = f"http://127.0.0.1:{server.port}/api/accel/metrics"

    def fetch() -> dict:
        with urllib.request.urlopen(url) as r:
            return json.loads(r.read())

    return sampler, server, fetch


async def _bench_scrape(backend: str, iters: int = 50, warmup: int = 5) -> dict:
    """Headline: scrape→render p50 against the live server."""
    sampler, server, fetch = await _serve_bench_app(backend)
    stop = threading.Event()
    if backend == "jax":  # fake counters are synthetic; no point burning
        _start_burn(stop)
    try:
        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await sampler.tick_fast()  # scrape: fresh device counters
            data = await asyncio.to_thread(fetch)  # render: HTTP + JSON
            dt = (time.perf_counter() - t0) * 1e3
            assert "chips" in data
            if i >= warmup:
                cycle_ms.append(dt)

        # Sampler-only rate (exporter samples/sec): how fast the device
        # counter loop can run, excluding HTTP.
        t0 = time.perf_counter()
        n = 50
        for _ in range(n):
            await sampler.tick_fast()
        samples_per_sec = n / (time.perf_counter() - t0)
    finally:
        stop.set()
        await server.stop()

    p50 = _p50(cycle_ms)
    return {
        "metric": "accel_scrape_to_render_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(5000.0 / p50, 1),
        "p95_ms": round(sorted(cycle_ms)[int(0.95 * len(cycle_ms)) - 1], 3),
        "sampler_samples_per_sec": round(samples_per_sec, 1),
        "chips": len(sampler.chips()),
        "accel_backend": backend,
    }


def _bench_kernels() -> dict:
    """PARITY kernel claims, re-measured: int8 matmul (Pallas vs XLA's
    fused dequant) and paged-attention decode (Pallas vs fused gather).
    Slope-timed (loadgen.burn.measure_*) so remote-dispatch overhead
    cancels. Real-MXU-only — interpret-mode numbers would be noise."""
    import dataclasses

    from tpumon.loadgen.burn import (
        measure_int8_tflops,
        measure_mxu_tflops,
        measure_paged_engine_step_ms,
        measure_paged_gbps,
    )
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig

    def safe(fn, **kw):
        # A single unresolvable measurement (roofline/noise guard raised
        # after retries, loadgen.burn._guarded_slope) nulls its own keys,
        # not the whole phase.
        try:
            return fn(**kw)
        except Exception as e:
            _note(f"kernel measurement {fn.__name__}({kw}) failed: {e}")
            return None

    mm_pallas = safe(measure_mxu_tflops, use_pallas=True)
    mm_xla = safe(measure_mxu_tflops, use_pallas=False)
    i8_pallas = safe(measure_int8_tflops, use_pallas=True)
    i8_xla = safe(measure_int8_tflops, use_pallas=False)
    pa_pallas = safe(measure_paged_gbps, use_pallas=True)
    pa_xla = safe(measure_paged_gbps, use_pallas=False)
    # The r05 ENGINE-STEP settlement of gather-vs-kernel (VERDICT r04
    # weak #1): the real serving step fn (paged_kv.paged_decode_step,
    # scan-fused so dispatch amortizes) at a production shape — 370M
    # params, 16 slots x 4k context, page 128, GQA 4 — where the KV
    # pool (537 MB/step streamed) dwarfs on-chip memory. This is the
    # regime the microbench above models; at the demo-scale serving
    # shape the pool fits cache and gather wins instead (BENCH_NOTES
    # r05 section has both numbers and the why).
    prod = ServeConfig(
        model=ModelConfig(vocab=4096, d_model=4096, n_layers=2,
                          n_heads=32, n_kv_heads=8, d_ff=8192,
                          max_seq=4096),
        slots=16, prefill_len=128, kv_layout="paged")
    es_gather = safe(measure_paged_engine_step_ms,
                     cfg=dataclasses.replace(prod, paged_attn="gather"),
                     inner_steps=16)
    es_kernel = safe(measure_paged_engine_step_ms,
                     cfg=dataclasses.replace(prod, paged_attn="kernel"),
                     inner_steps=16)

    def val(out, key, digits):
        return round(out[key], digits) if out else None

    def ratio(a, b, key):
        return round(a[key] / b[key], 2) if a and b else None

    return {
        "mxu_matmul_pallas_tflops": val(mm_pallas, "tflops", 2),
        "mxu_matmul_xla_tflops": val(mm_xla, "tflops", 2),
        "mxu_matmul_vs_xla": ratio(mm_pallas, mm_xla, "tflops"),
        "int8_matmul_pallas_tflops": val(i8_pallas, "tflops", 2),
        "int8_matmul_xla_tflops": val(i8_xla, "tflops", 2),
        "int8_matmul_vs_xla": ratio(i8_pallas, i8_xla, "tflops"),
        "paged_attention_pallas_kv_gbps": val(pa_pallas, "kv_gbps", 1),
        "paged_attention_xla_kv_gbps": val(pa_xla, "kv_gbps", 1),
        "paged_attention_vs_xla": ratio(pa_pallas, pa_xla, "kv_gbps"),
        # Production-shape engine step (ms; lower is better) — the
        # kernel/gather ratio is inverted from ms so >1 still means
        # "kernel faster".
        "paged_engine_step_gather_ms": val(es_gather, "ms_per_step", 3),
        "paged_engine_step_kernel_ms": val(es_kernel, "ms_per_step", 3),
        "paged_engine_step_kernel_vs_gather": ratio(
            es_gather, es_kernel, "ms_per_step"),
        # Per-measurement marginal durations: the slope each number came
        # from resolved this much device time above the tunnel's ±60 ms
        # per-call noise (roofline+noise-floor guards in loadgen.burn).
        "kernel_marginal_s": {
            "mxu_pallas": val(mm_pallas, "marginal_s", 3),
            "mxu_xla": val(mm_xla, "marginal_s", 3),
            "int8_pallas": val(i8_pallas, "marginal_s", 3),
            "int8_xla": val(i8_xla, "marginal_s", 3),
            "paged_pallas": val(pa_pallas, "marginal_s", 3),
            "paged_xla": val(pa_xla, "marginal_s", 3),
            "engine_step_gather": val(es_gather, "marginal_s", 3),
            "engine_step_kernel": val(es_kernel, "marginal_s", 3),
        },
    }


def _bench_train(on_tpu: bool) -> dict:
    """Trainer MFU (achieved model FLOP/s over device peak) + tokens/s,
    measured with the whole step loop fused into one jitted scan
    (loadgen.train.fused_train_bench) so the number reflects device
    throughput, not Python dispatch or tunnel RTT. Off-TPU shapes shrink
    to keep CI fast (MFU is null there — no known peak for CPU).

    train_seq8k_mfu pins the flash fwd+bwd kernel schedule at seq 8192
    WITHOUT remat (r05: the kernel never materializes T^2, so full
    residuals fit 16 GiB); the r02-r04 long-sequence features (per-layer
    remat + chunked online-softmax attention) stay measured under
    train_seq8k_chunked_mfu_pct."""
    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.train import TrainConfig, fused_train_bench

    import dataclasses

    if on_tpu:
        # d2048/L6 seq-1024: headline schedule is now the r05 flash
        # kernel pair (triangle fwd + bwd, loadgen.model
        # attention="flash") — naive's [B,H,T,T] score materialization
        # traffic, not its FLOPs, was costing ~30% wall clock
        # (55.5 -> 72.2% MFU measured; BENCH_NOTES r05). The old
        # schedule stays pinned as train_mfu_naive_pct so either
        # path's regression is visible per round.
        model = ModelConfig(
            vocab=4096, d_model=2048, n_layers=6, n_heads=16, n_kv_heads=16,
            d_ff=8192, max_seq=1024, attention="flash", attn_block_k=512,
        )
        cfg = TrainConfig(model=model, batch=8, seq=1024)
        steps = 16
        # seq-8192: flash/1024 WITHOUT remat — the kernel never
        # materializes T^2, so the shape now fits 16 GiB with full
        # residuals (r04 needed remat + the jnp-chunked schedule;
        # that path stays pinned as train_seq8k_chunked_mfu_pct).
        model_8k = ModelConfig(
            vocab=4096, d_model=2048, n_layers=6, n_heads=16, n_kv_heads=16,
            d_ff=8192, max_seq=8192,
            attention="flash", attn_block_k=1024,
        )
        cfg_8k = TrainConfig(model=model_8k, batch=1, seq=8192)
        steps_8k = 4
        alt = fused_train_bench(TrainConfig(
            model=dataclasses.replace(model, attention="naive"),
            batch=8, seq=1024), steps=steps)
        alt_8k = fused_train_bench(TrainConfig(
            model=dataclasses.replace(
                model_8k, remat=True, attention="chunked",
                attn_block_k=512),
            batch=1, seq=8192), steps=steps_8k)
    else:
        model = ModelConfig()
        cfg = TrainConfig(model=model, batch=2, seq=64)
        steps = 4
        model_8k = ModelConfig(
            remat=True, attention="chunked", attn_block_k=64, max_seq=256
        )
        cfg_8k = TrainConfig(model=model_8k, batch=1, seq=256)
        steps_8k = 2
        alt = alt_8k = None
    out = fused_train_bench(cfg, steps=steps)
    out_8k = fused_train_bench(cfg_8k, steps=steps_8k)
    return {
        "train_mfu_pct": round(out["mfu_pct"], 2)
        if out["mfu_pct"] is not None
        else None,
        "train_tokens_per_sec": round(out["tokens_per_sec"], 1),
        "train_mfu_naive_pct": round(alt["mfu_pct"], 2)
        if alt and alt["mfu_pct"] is not None else None,
        "train_seq8k_mfu_pct": round(out_8k["mfu_pct"], 2)
        if out_8k["mfu_pct"] is not None
        else None,
        "train_seq8k_tokens_per_sec": round(out_8k["tokens_per_sec"], 1),
        "train_seq8k_chunked_mfu_pct": round(alt_8k["mfu_pct"], 2)
        if alt_8k and alt_8k["mfu_pct"] is not None else None,
    }


def _bench_serving(on_tpu: bool) -> dict:
    """End-to-end engine throughput across the whole feature matrix:
    dense step decode, fused block decode, speculative decoding (with
    measured acceptance), paged KV, int8 KV, and prefix-cache TTFT —
    every serving perf claim gets a keyed per-round number (VERDICT r02
    item #4). Tokens/s = generated tokens / wall time including prefill
    (the serving-loop number PARITY claims)."""
    import dataclasses

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import (
        ServeConfig,
        ServingEngine,
        default_engine_config,
    )

    if on_tpu:
        base = ServeConfig(
            model=ModelConfig(vocab=4096, d_model=512, n_layers=4,
                              n_heads=8, n_kv_heads=8, d_ff=2048,
                              max_seq=512),
            slots=8, prefill_len=32,
        )
        n_req, max_new = 24, 64
    else:
        base = default_engine_config()
        n_req, max_new = 8, 16
    prompt = list(range(1, 17))

    def run(fragment: bool = False, **over) -> tuple[float, "ServingEngine"]:
        engine = ServingEngine(dataclasses.replace(base, **over))
        # Warmup: compile prefill + decode out of the measured window.
        engine.submit(prompt, max_new=2)
        engine.drain()
        if fragment:
            # Deliberately fragment the page pool before the measured
            # window: interleaved request lifetimes (staggered max_new)
            # return pages to the free list out of allocation order, so
            # the measured requests get scrambled page tables — the
            # post-churn steady state a long-lived server actually runs
            # in, and the layout where the gather/kernel read paths
            # diverge (ops/paged_attention module docstring).
            for _ in range(3):
                churn = [engine.submit(prompt, max_new=4 + 17 * (i % 3))
                         for i in range(n_req)]
                engine.drain()
                assert all(r.done.is_set() for r in churn)
        t0 = time.perf_counter()
        reqs = [engine.submit(prompt, max_new=max_new) for _ in range(n_req)]
        engine.drain()
        tps = sum(len(r.output) for r in reqs) / (time.perf_counter() - t0)
        return tps, engine

    def spec_accept(engine) -> float | None:
        from tpumon.collectors.serving import distill_serving_metrics

        return distill_serving_metrics(engine.metrics_text()).get(
            "spec_accept_pct"
        )

    def prefix_ttft(**over) -> tuple[float, float, dict]:
        """Median TTFT (ms) over repeated cold/hit pairs.

        r03's number was meaningless twice over: the 16-token prompt was
        SHORTER than one prefill chunk (store/restore both no-ops — the
        "hit" leg was a second cold miss), and each leg was timed once
        on a tunnel with ±60 ms per-call noise (VERDICT r03 weak #3).
        Now: prompts span many chunks so a hit elides all but the final
        prefill dispatch; each pair uses a DISTINCT prompt (cold by
        construction) then resubmits it (chunk-aligned prefix hit);
        pairs accumulate until the observed spread is below the measured
        effect (or a cap); medians + spreads are published.

        ``over``: extra ServeConfig fields — kv_layout="paged" measures
        the page-SHARING cache (zero-copy hits) vs dense's HBM restore.
        """
        engine = ServingEngine(
            dataclasses.replace(base, prefix_cache_entries=24, **over)
        )
        # As many prompt chunks as max_seq allows (+decode headroom):
        # the elided prefill must dwarf the tunnel's per-call noise.
        chunks = (base.model.max_seq - base.prefill_len) // base.prefill_len
        plen = base.prefill_len * chunks
        vocab = base.model.vocab

        def mk(seed: int) -> list:
            return [1 + (seed * 131 + i * 7) % (vocab - 1)
                    for i in range(plen)]

        # Warmup compiles every path in the measured window: prefill,
        # decode, extract (store on first submit), restore (second).
        engine.submit(mk(0), max_new=2)
        engine.drain()
        engine.submit(mk(0), max_new=2)
        engine.drain()

        def ttft(p) -> float:
            t0 = time.perf_counter()
            engine.submit(p, max_new=1)
            engine.drain()
            return (time.perf_counter() - t0) * 1e3

        import statistics

        median = statistics.median

        def iqr(xs: list) -> float:
            q = statistics.quantiles(xs, n=4)
            return q[2] - q[0]

        colds, hits_ms = [], []
        for pair in range(1, 25):
            p = mk(pair)
            colds.append(ttft(p))   # distinct prompt: never cached
            hits_ms.append(ttft(p))  # same prompt: prefix hit
            if pair >= 6:
                effect = median(colds) - median(hits_ms)
                # Decisive means effect > 2x the IQR of BOTH legs
                # (r05 tightening, VERDICT r04 weak #5 — the r04 rule
                # stopped at the margin). IQR, not max-min: a single
                # tunnel hiccup must not run the loop to the cap.
                if effect > 0 and 2 * max(iqr(colds), iqr(hits_ms)) < effect:
                    break
        # Cross-check: the hit leg elides (chunks-1) prefill dispatches,
        # so the cold-hit delta should be ~their directly-measured cost.
        # Slope it from cold TTFTs of distinct NEVER-CACHED prompts at
        # two chunk counts (same submit->first-token path, so dispatch
        # overhead and the decode step cancel in the subtraction).
        short_chunks = max(1, chunks // 3)

        def mk_at(seed: int, n_chunks: int) -> list:
            return [1 + (seed * 173 + i * 11) % (vocab - 1)
                    for i in range(base.prefill_len * n_chunks)]

        long_c = [ttft(mk_at(100 + i, chunks)) for i in range(5)]
        short_c = [ttft(mk_at(200 + i, short_chunks)) for i in range(5)]
        per_chunk = ((median(long_c) - median(short_c))
                     / (chunks - short_chunks))
        effect = median(colds) - median(hits_ms)
        stats = {
            "pairs": len(colds),
            "cold_iqr_ms": round(iqr(colds), 1),
            "hit_iqr_ms": round(iqr(hits_ms), 1),
            "prompt_tokens": plen,
            "cached_prefix_tokens": base.prefill_len * (chunks - 1),
            # effect vs 2x-IQR decisiveness + the elided-work oracle:
            # per-chunk prefill cost (slope of cold TTFT over chunk
            # count) x chunks elided. If effect_ms and
            # expected_elided_ms disagree wildly, either the hit path
            # carries hidden overhead or the bench is reading noise.
            "effect_ms": round(effect, 1),
            "decisive": bool(
                effect > 0
                and 2 * max(iqr(colds), iqr(hits_ms)) < effect),
            "per_chunk_prefill_ms": round(per_chunk, 2),
            "expected_elided_ms": round(per_chunk * (chunks - 1), 1),
        }
        return median(colds), median(hits_ms), stats

    def spec_prompt_bench() -> dict:
        """Prompt-lookup speculation on the workload it exists for
        (VERDICT r04 weak #2 — "make speculative decoding win one
        honest benchmark"). Honesty frame: the workload is repetitive
        BY CONSTRUCTION (periodic token patterns — the
        extraction/quote/code-edit regime prompt lookup targets), and
        the target model is TRAINED here, with the in-repo trainer, to
        actually continue the repetition — acceptance against an
        untrained target would be noise, not a measurement. The
        comparison is plain block-8 decode of the SAME trained model on
        the SAME prompts: identical outputs (greedy lossless), only
        the schedule differs.
        """
        from tpumon.loadgen.train import train_induction

        m = base.model
        period, seq = 16, min(256, m.max_seq)
        steps = 2000 if on_tpu else 40
        trained, losses = train_induction(
            m, steps=steps, period=period, seq=seq)

        def mk_prompt(i: int) -> list:
            rng = [1 + (i * 997 + j * 131) % (m.vocab - 1)
                   for j in range(period)]
            reps = -(-48 // period)
            return (rng * reps)[:48]  # 3 periods of context

        new = min(160, m.max_seq - 64)

        def measure(**over) -> tuple[float, "ServingEngine"]:
            eng = ServingEngine(
                dataclasses.replace(base, **over), params=trained)
            eng.submit(mk_prompt(999), max_new=4)
            eng.drain()
            t0 = time.perf_counter()
            reqs = [eng.submit(mk_prompt(i), max_new=new)
                    for i in range(n_req)]
            eng.drain()
            tps = sum(len(r.output) for r in reqs) / (
                time.perf_counter() - t0)
            return tps, eng

        tps_plain, _ = measure(decode_block=8)
        tps_pl, eng_pl = measure(spec_len=15, spec_source="prompt")
        accept = spec_accept(eng_pl)
        return {
            "serving_copy_block8_tokens_per_sec": round(tps_plain, 1),
            "serving_spec_prompt_tokens_per_sec": round(tps_pl, 1),
            "serving_spec_prompt_accept_pct": round(accept, 1)
            if accept is not None else None,
            "serving_spec_prompt_vs_block8": round(tps_pl / tps_plain, 2)
            if tps_plain else None,
            "serving_spec_prompt_workload": {
                "period": period, "prompt_tokens": 48, "max_new": new,
                "train_steps": steps,
                "train_loss_first": round(float(losses[0]), 3),
                "train_loss_last": round(float(losses[-1]), 3),
            },
        }

    tps_step, _ = run()
    # Fused plain decode (ServeConfig.decode_block): 8 steps per
    # dispatch — the engine's dispatch-overhead amortization.
    tps_block, _ = run(decode_block=8)
    tps_spec, eng_spec = run(spec_len=3)
    # Speculative decoding with a REAL draft (half the target's layers,
    # sharing its weights — engine truncated-draft init): acceptance is
    # a measured property of draft/target agreement, not the r03
    # self-speculation tautology (VERDICT r03 weak #4). Honest
    # comparison point: the equal-settings plain block-decode number.
    draft_layers = max(1, base.model.n_layers // 2)
    tps_spec_draft, eng_spec_draft = run(
        spec_len=3,
        draft_model=dataclasses.replace(base.model, n_layers=draft_layers))
    # pool_pages=0 = the dense-equivalent pool the engine computes itself
    # (slots*max_pages+1): measures the paged indirection at equal memory.
    tps_paged, _ = run(decode_block=8, kv_layout="paged")
    # The r05 settlement of the gather-vs-kernel question at ENGINE
    # level (VERDICT r04 weak #1): same workload on a deliberately
    # fragmented pool, XLA fused-gather read vs the Pallas kernel
    # (ServeConfig.paged_attn) — the microbench's 1.98x KV-streaming
    # gap (paged_attention_vs_xla above) diluted by the step's weight
    # traffic and the serving loop around it.
    tps_paged_frag, _ = run(decode_block=8, kv_layout="paged",
                            fragment=True)
    tps_paged_kernel, _ = run(decode_block=8, kv_layout="paged",
                              paged_attn="kernel", fragment=True)
    # Speculative verify over the paged pool (r04: paged_decode_block) —
    # self-speculation, so this isolates the paged-verify overhead vs
    # the dense spec number above at equal acceptance.
    tps_paged_spec, _ = run(spec_len=3, kv_layout="paged")
    tps_int8kv, _ = run(decode_block=8, kv_dtype="int8")
    spec_prompt = spec_prompt_bench()
    ttft_cold, ttft_hit, ttft_stats = prefix_ttft()
    pttft_cold, pttft_hit, pttft_stats = prefix_ttft(
        kv_layout="paged", decode_block=8)
    accept = spec_accept(eng_spec)
    accept_draft = spec_accept(eng_spec_draft)
    return {
        "serving_tokens_per_sec": round(tps_step, 1),
        "serving_block8_tokens_per_sec": round(tps_block, 1),
        "serving_spec_tokens_per_sec": round(tps_spec, 1),
        # A missing acceptance metric must null, not fabricate 0%.
        "serving_spec_accept_pct": round(accept, 1)
        if accept is not None else None,
        "serving_spec_draft_layers": draft_layers,
        "serving_spec_draft_tokens_per_sec": round(tps_spec_draft, 1),
        "serving_spec_draft_accept_pct": round(accept_draft, 1)
        if accept_draft is not None else None,
        **spec_prompt,
        "serving_paged_block8_tokens_per_sec": round(tps_paged, 1),
        # Fragmented-pool pair: same config, scrambled page tables.
        "serving_paged_frag_block8_tokens_per_sec": round(tps_paged_frag, 1),
        "serving_paged_kernel_block8_tokens_per_sec": round(
            tps_paged_kernel, 1),
        "serving_paged_kernel_vs_gather": round(
            tps_paged_kernel / tps_paged_frag, 2) if tps_paged_frag else None,
        "serving_paged_spec_tokens_per_sec": round(tps_paged_spec, 1),
        "serving_int8kv_block8_tokens_per_sec": round(tps_int8kv, 1),
        "serving_prefix_ttft_cold_ms": round(ttft_cold, 1),
        "serving_prefix_ttft_hit_ms": round(ttft_hit, 1),
        "serving_prefix_ttft_stats": ttft_stats,
        # Paged layout: hits point the page table at shared pages —
        # zero HBM copy (the dense cache's restore is a copy).
        "serving_paged_prefix_ttft_cold_ms": round(pttft_cold, 1),
        "serving_paged_prefix_ttft_hit_ms": round(pttft_hit, 1),
        "serving_paged_prefix_ttft_stats": pttft_stats,
        "serving_requests": n_req,
    }


def _bench_serving_concurrency(on_tpu: bool) -> dict:
    """Continuous-batching scheduler comparison at 32- and 128-way
    concurrency (ROADMAP item 4's missing numbers): a multi-tenant
    burst — a long-prompt (RAG-style) tenant ahead of short chat
    traffic, the worst-case head-of-line order — served once by the
    interleaved chunked-prefill scheduler and once by the sequential
    stop-the-world baseline (``ServeConfig.scheduler``), on otherwise
    identical engines. Slots == concurrency: the running batch IS the
    concurrency level (continuous batching's premise), so TTFT measures
    prefill *scheduling*, not queue depth.

    Reported: aggregate tokens/s under the interleaved scheduler at
    both levels, and TTFT p95 at 128-way under both schedulers — the
    sequential number is the stop-the-world interference the
    interleaved scheduler exists to remove (the p95 request is a chat
    request stuck behind the long-prompt burst). Schedulers are run in
    alternating repetitions with best-of per scheduler (this box's
    noise is multiplicative drift, so pairing + best-of beats
    averaging); greedy decoding and per-(request, index) sampling keys
    make the token streams identical across all runs — only the
    schedule differs."""
    import random

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig, ServingEngine

    p = 32  # prefill chunk / page size (tokens)
    long_chunks = 32
    model = ModelConfig(vocab=1024, d_model=128, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=512,
                        max_seq=p * (long_chunks + 1))

    def mk_mix(n_conc: int, n_long: int, seed: int) -> list:
        rng = random.Random(seed)
        reqs = []
        for i in range(n_conc):
            if i < n_long:
                # Long-prompt tenant bursts FIRST: every chat request
                # behind it eats the whole burst's prefill under
                # stop-the-world admission.
                plen, mx = p * long_chunks - 3, 4
            else:
                plen, mx = rng.randint(10, p - 2), 4
            prompt = [1 + (i * 17 + j * 7) % (model.vocab - 1)
                      for j in range(plen)]
            reqs.append((prompt, mx))
        return reqs

    def build(n_conc: int, scheduler: str) -> "ServingEngine":
        eng = ServingEngine(ServeConfig(
            model=model, slots=n_conc, prefill_len=p,
            scheduler=scheduler, decode_block=4),
            max_queue=n_conc + 8)
        # Warmup compiles prefill + block/single decode out of the
        # measured window.
        eng.submit(list(range(8)), max_new=6)
        eng.drain()
        eng.submit(list(range(model.max_seq - 8)), max_new=6)
        eng.drain()
        return eng

    def one_rep(eng: "ServingEngine", n_conc: int, n_long: int,
                seed: int) -> tuple[float, float]:
        mix = mk_mix(n_conc, n_long, seed)
        t0 = time.perf_counter()
        reqs = [eng.submit(pr, max_new=mx) for pr, mx in mix]
        eng.drain(max_steps=1_000_000)
        wall = time.perf_counter() - t0
        assert all(r.done.is_set() for r in reqs)
        tokens = sum(len(r.output) for r in reqs)
        ttfts = sorted(r.ttft_s for r in reqs)
        p95 = ttfts[int(0.95 * (len(ttfts) - 1))] * 1e3
        return tokens / wall, p95

    def compare(n_conc: int, n_long: int, reps: int = 2) -> dict:
        engines = {s: build(n_conc, s)
                   for s in ("interleaved", "sequential")}
        got: dict[str, list] = {s: [] for s in engines}
        for rep in range(reps):
            for sched, eng in engines.items():  # alternating pairs
                got[sched].append(one_rep(eng, n_conc, n_long, rep))
        return {
            sched: (max(v[0] for v in vals),  # best tokens/s
                    min(v[1] for v in vals))  # best-case p95
            for sched, vals in got.items()
        }

    c32 = compare(32, 2)
    c128 = compare(128, 6)
    int32, seq32 = c32["interleaved"], c32["sequential"]
    int128, seq128 = c128["interleaved"], c128["sequential"]
    return {
        "serving_conc32_tokens_per_sec": round(int32[0], 1),
        "serving_conc128_tokens_per_sec": round(int128[0], 1),
        "serving_conc128_ttft_p95_ms": round(int128[1], 1),
        "serving_conc128_ttft_p95_sequential_ms": round(seq128[1], 1),
        # Context for the record keys (full results only).
        "serving_conc32_ttft_p95_ms": round(int32[1], 1),
        "serving_conc32_ttft_p95_sequential_ms": round(seq32[1], 1),
        "serving_conc32_tokens_per_sec_sequential": round(seq32[0], 1),
        "serving_conc128_tokens_per_sec_sequential": round(seq128[0], 1),
        "serving_conc128_ttft_p95_speedup": round(
            seq128[1] / int128[1], 2) if int128[1] else None,
        "serving_conc128_tps_vs_sequential": round(
            int128[0] / seq128[0], 3) if seq128[0] else None,
        "serving_concurrency_workload": {
            "prefill_chunk_tokens": p, "long_chunks": long_chunks,
            "long_requests": {"conc32": 2, "conc128": 6},
            "short_max_new": 4, "decode_block": 4,
            "slots": "== concurrency", "reps": 2,
        },
    }


def _bench_serving_mesh(on_tpu: bool) -> dict:
    """Mesh serving (docs/perf.md "Mesh serving"): a dp×tp
    MeshServingEngine against the single-chip engine it replaces, at a
    FIXED per-chip KV budget, on a multi-tenant 128-request burst where
    every tenant shares a 6-page system prefix (the rag/chat traffic
    shape). The mesh's win on a serialized fake backend is *elided
    work*, not parallel compute (every fake device shares one core):
    the 8 tenants' retained prefix pages (48) exceed one chip's pool
    (32), so the single-chip baseline thrashes — round-robin arrivals
    evict exactly the LRU tenant the next admission needs, and most
    requests re-prefill all 6 prefix pages. The mesh's dp=4 replicas
    each own a chip's pool, and the router's prefix affinity parks 2
    tenants per replica (12 retained pages — fits under slot
    pressure), so repeats prefill only their unique tail. That is the
    production claim in miniature: the mesh's aggregate KV holds the
    tenant working set one chip cannot. (dp=4×tp=1: tensor-parallel
    KV sharding doesn't compose with prefix caching — ServeConfig
    rejects it — and on an emulated single-core backend the tp
    collective tax would measure the simulator, not the engine.)
    Greedy decoding + per-(request id, token index) sampling keys make
    the token streams bit-identical across both engines — only
    placement and cache residency differ.

    Also measured: the ring-attention admission ceiling. A flat paged
    engine refuses prompts past one chip's stripe (max_seq - 1); with
    ``ring_stripes=N`` the same model admits N×max_seq - 1 by paging KV
    block-wise around the tp ring. The reported ceiling is *served*
    (the request must complete), not computed from the config."""
    import jax

    from tpumon.loadgen.model import ModelConfig
    from tpumon.loadgen.serving import ServeConfig, make_serving_engine

    dp, tp = 4, 1
    if len(jax.devices()) < dp * tp:
        return {}

    p = 32  # prefill chunk / page size (tokens)
    # float32: the bit-identity contract (tests/test_scheduler.py's
    # golden matrix) holds in f32 — bf16's rounding wobbles near-tie
    # argmaxes under tp-sharded reductions.
    model = ModelConfig(vocab=1024, d_model=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, d_ff=256, max_seq=8 * p,
                        compute_dtype="float32")
    slots_chip = 8     # slots whose live KV fits one chip's HBM stripe
    pages_chip = 32    # one chip's page budget
    n_conc = 128
    n_prefixes = 8
    prefix_tokens = 6 * p  # six pages of shared system prompt per tenant
    max_new = 4

    def mk_burst(seed: int) -> list:
        import random

        rng = random.Random(seed)
        out = []
        for i in range(n_conc):
            t = i % n_prefixes  # tenant = shared system prefix
            prefix = [1 + (t * 131 + j * 7) % (model.vocab - 1)
                      for j in range(prefix_tokens)]
            tail = [1 + (i * 17 + j * 11) % (model.vocab - 1)
                    for j in range(rng.randint(8, 20))]
            out.append((prefix + tail, max_new))
        return out

    def build(mesh_dp: int, mesh_tp: int):
        eng = make_serving_engine(ServeConfig(
            model=model, slots=slots_chip * mesh_tp, prefill_len=p,
            kv_layout="paged", pool_pages=pages_chip * mesh_tp,
            prefix_cache_entries=n_prefixes,
            mesh_dp=mesh_dp, mesh_tp=mesh_tp),
            max_queue=n_conc + 8)
        # Compile out of the window — one warm request per replica
        # (each replica holds its own jitted closures; the load-balance
        # tiebreak spreads equal-length no-hit prompts round-robin).
        for k in range(max(1, mesh_dp)):
            eng.submit(list(range(2 + k, 10 + k)), max_new=6)
        eng.drain()
        return eng

    def one_rep(eng, seed: int) -> tuple[float, float, list]:
        burst = mk_burst(seed)
        t0 = time.perf_counter()
        reqs = [eng.submit(pr, max_new=mx) for pr, mx in burst]
        eng.drain(max_steps=1_000_000)
        wall = time.perf_counter() - t0
        assert all(r.done.is_set() for r in reqs)
        tokens = sum(len(r.output) for r in reqs)
        ttfts = sorted(r.ttft_s for r in reqs)
        p95 = ttfts[int(0.95 * (len(ttfts) - 1))] * 1e3
        return tokens / wall, p95, [r.output for r in reqs]

    engines = {"mesh": build(dp, tp), "single": build(1, 1)}
    got: dict[str, list] = {k: [] for k in engines}
    streams: dict[str, list] = {}
    for rep in range(2):
        for kind, eng in engines.items():  # alternating pairs
            tps, p95, outs = one_rep(eng, rep)
            got[kind].append((tps, p95))
            if rep == 0:
                streams[kind] = outs
    # The perf claim rides on the equivalence claim.
    assert streams["mesh"] == streams["single"], "mesh streams diverged"
    mesh_tps = max(v[0] for v in got["mesh"])
    single_tps = max(v[0] for v in got["single"])
    mesh_p95 = min(v[1] for v in got["mesh"])
    single_p95 = min(v[1] for v in got["single"])

    # Ring admission ceiling: longest prompt actually SERVED, flat vs
    # ring (ring_stripes widens the page table tp-ring-wise; tp=1 here —
    # admission is a table-geometry property, not a device-count one).
    stripes = 4
    ring_max = flat_max = None
    for ring, cap in ((0, model.max_seq), (stripes, stripes * model.max_seq)):
        eng = make_serving_engine(ServeConfig(
            model=model, slots=1, prefill_len=p, kv_layout="paged",
            pool_pages=2 * stripes * (model.max_seq // p),
            ring_stripes=ring))
        over = eng.submit(list(range(2, cap + 2)), max_new=1)  # cap tokens
        r = eng.submit([1 + j % (model.vocab - 1) for j in range(cap - 1)],
                       max_new=1)
        eng.drain(max_steps=1_000_000)
        assert over.status == "rejected" and r.status == "completed"
        if ring:
            ring_max = cap - 1
        else:
            flat_max = cap - 1

    return {
        "serving_mesh_128_tokens_per_sec": round(mesh_tps, 1),
        "serving_single_128_tokens_per_sec": round(single_tps, 1),
        "serving_mesh_128_tps_vs_single": round(
            mesh_tps / single_tps, 2) if single_tps else None,
        "serving_mesh_ttft_p95_ms": round(mesh_p95, 1),
        "serving_single_ttft_p95_ms": round(single_p95, 1),
        "serving_ring_max_context_tokens": ring_max,
        "serving_ring_flat_max_context_tokens": flat_max,
        "serving_mesh_workload": {
            "mesh": f"{dp}x{tp}", "slots_per_chip": slots_chip,
            "requests": n_conc, "max_new": max_new,
            "prefill_chunk_tokens": p, "kv_layout": "paged",
            "ring_stripes": stripes, "reps": 2,
        },
    }


async def _bench_fastpath(topology: str, iters: int = 30, warmup: int = 5) -> dict:
    """Data-plane fast path at production chip counts (docs/perf.md):
    single instance on a fake v5p topology, measuring the epoch-cached
    render path — realtime scrape→render p50, exporter cold render vs
    cached re-render (same tick), and the SSE keyframe vs delta frame
    bytes. Key suffix = chip count, so 64 vs 256 scale per round."""
    from tpumon.exporter import render_exporter

    sampler, server, fetch = await _serve_bench_app(f"fake:{topology}")
    try:
        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await sampler.tick_fast()
            data = await asyncio.to_thread(fetch)
            if i >= warmup:
                cycle_ms.append((time.perf_counter() - t0) * 1e3)
        n = len(data["chips"])

        # Exporter: cold render (no cache — every block re-walks its
        # section) vs cached re-render within one tick (every block is
        # a version hit; tpumon.snapshot.ExporterCache).
        cold_ms: list[float] = []
        for _ in range(20):
            t0 = time.perf_counter()
            text = render_exporter(sampler)
            cold_ms.append((time.perf_counter() - t0) * 1e3)
        assert "tpu_mxu_duty_cycle_pct" in text
        render_exporter(sampler, cache=server.exporter_cache)  # prime
        cached_ms: list[float] = []
        for _ in range(20):
            t0 = time.perf_counter()
            render_exporter(sampler, cache=server.exporter_cache)
            cached_ms.append((time.perf_counter() - t0) * 1e3)

        # SSE wire: full keyframe vs the steady-state delta frame for
        # one tick of fake-backend movement (every chip's gauges move
        # each tick — real clusters delta smaller than this).
        key_frame, ver, _ = server._sse_frame(-1, True)
        await sampler.tick_fast()
        delta_frame, _, was_key = server._sse_frame(ver, False)
        assert not was_key
    finally:
        await server.stop()

    return {
        f"fastpath_{n}_scrape_to_render_p50_ms": round(_p50(cycle_ms), 3),
        f"exporter_render_{n}_ms": round(_p50(cold_ms), 3),
        f"exporter_cached_render_{n}_ms": round(_p50(cached_ms), 3),
        f"sse_keyframe_bytes_{n}": len(key_frame),
        f"sse_delta_bytes_{n}": len(delta_frame),
    }


async def _bench_observability(
    topology: str = "v5p-64", iters: int = 40, warmup: int = 5
) -> dict:
    """Self-tracing overhead (docs/observability.md): tick p50 and
    scrape→render p50 with the span ring at its default capacity vs
    tracing disabled, at a production chip count. The acceptance bar is
    the ``trace_overhead_scrape_pct`` key staying under ~5% — tracing
    is always-on, so its cost IS a headline number."""
    measured: dict[str, tuple[float, float]] = {}
    spans_recorded = 0
    # A/B/A/B with per-config min-of-rounds: the two configs are
    # measured tens of seconds apart, so box-level load drift would
    # otherwise dominate the sub-5% effect being measured.
    for _round in range(2):
        for label, ring in (("on", "4096"), ("off", "0")):
            sampler, server, fetch = await _serve_bench_app(
                f"fake:{topology}", TPUMON_TRACE_RING=ring
            )
            try:
                tick_ms: list[float] = []
                for i in range(warmup + iters):
                    t0 = time.perf_counter()
                    await sampler.tick_fast()
                    if i >= warmup:
                        tick_ms.append((time.perf_counter() - t0) * 1e3)
                cycle_ms: list[float] = []
                for i in range(warmup + iters):
                    t0 = time.perf_counter()
                    await sampler.tick_fast()
                    data = await asyncio.to_thread(fetch)
                    if i >= warmup:
                        cycle_ms.append((time.perf_counter() - t0) * 1e3)
                assert "chips" in data
                if label == "on":
                    spans_recorded = sampler.tracer.recorded
            finally:
                await server.stop()
            pair = (_p50(tick_ms), _p50(cycle_ms))
            prev = measured.get(label)
            measured[label] = (
                pair if prev is None
                else (min(prev[0], pair[0]), min(prev[1], pair[1]))
            )

    def pct(on: float, off: float) -> float | None:
        return round(100.0 * (on - off) / off, 2) if off > 0 else None

    (tick_on, scrape_on), (tick_off, scrape_off) = measured["on"], measured["off"]
    return {
        "trace_on_tick_p50_ms": round(tick_on, 3),
        "trace_off_tick_p50_ms": round(tick_off, 3),
        "trace_overhead_tick_pct": pct(tick_on, tick_off),
        "trace_on_scrape_to_render_p50_ms": round(scrape_on, 3),
        "trace_off_scrape_to_render_p50_ms": round(scrape_off, 3),
        "trace_overhead_scrape_pct": pct(scrape_on, scrape_off),
        "trace_spans_recorded": spans_recorded,
    }


async def _bench_events(
    topology: str = "v5p-64", iters: int = 60, warmup: int = 5
) -> dict:
    """Event journal + anomaly overhead (docs/events.md): raw journal
    append p50 (µs — the record() hot path every subsystem calls), and
    the EWMA detector bank's per-tick cost at a production chip count,
    measured as tick p50 with anomaly_detect on vs off. Like tracing,
    the detector is always-on by default, so its cost is a number of
    record (target <1%)."""
    from tpumon.events import EventJournal

    # Journal append microbench: alternating kinds/severities so the
    # counts dict sees its steady-state shape, attrs present like a
    # real breaker/anomaly event.
    journal = EventJournal(4096)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        journal.record(
            "breaker" if i % 2 else "anomaly",
            "minor" if i % 3 else "serious",
            "bench", "synthetic event", state="open", z=3.2,
        )
    append_us = (time.perf_counter() - t0) / n * 1e6
    assert journal.dropped == n - journal.capacity

    # Detector overhead: A/B interleaved min-of-rounds, same harness
    # discipline as the observability phase (the two configs differ
    # ONLY in TPUMON_ANOMALY_DETECT). Three rounds: the effect being
    # measured is ~1% of a ~5 ms tick, well under box-load drift.
    measured: dict[str, float] = {}
    for _round in range(3):
        for label, flag in (("on", "1"), ("off", "0")):
            sampler, server, fetch = await _serve_bench_app(
                f"fake:{topology}", TPUMON_ANOMALY_DETECT=flag
            )
            try:
                tick_ms: list[float] = []
                for i in range(warmup + iters):
                    t0 = time.perf_counter()
                    await sampler.tick_fast()
                    if i >= warmup:
                        tick_ms.append((time.perf_counter() - t0) * 1e3)
                if label == "on":
                    assert sampler.anomaly is not None
                else:
                    assert sampler.anomaly is None
            finally:
                await server.stop()
            p = _p50(tick_ms)
            measured[label] = min(measured.get(label, p), p)

    on, off = measured["on"], measured["off"]
    return {
        "events_append_p50_us": round(append_us, 3),
        "anomaly_on_tick_p50_ms": round(on, 3),
        "anomaly_off_tick_p50_ms": round(off, 3),
        "anomaly_overhead_tick_pct": (
            round(100.0 * (on - off) / off, 2) if off > 0 else None
        ),
    }


def _bench_history() -> dict:
    """Columnar history engine (docs/perf.md "history engine"): record
    p50 (µs/point) through the live RingHistory.record path, the 30 m
    fleet-query p50 (ms) with a tick landing between queries (so the
    resample memo can't serve stale bytes), resident bytes/point vs a
    tuple-deque holding the same stream (the ≥4x claim of record),
    binary-vs-json snapshot write + restore (ms), and per-chip
    recording at v5p-256 scale (256 chips × 4 metrics per tick)."""
    import os
    import tempfile
    from collections import deque

    from tpumon.history import (
        PROM_QUERIES,
        HistoryService,
        HistorySnapshotter,
        RingHistory,
    )

    base = 1_700_000_000.0

    # Record hot path: the batch ingest spine (record_series — one
    # quantize pass + columnar extend + per-batch downsample, native
    # kernel when built; docs/perf.md "ingest spine"). The per-point
    # record() shim is measured alongside into the full results.
    ring = RingHistory()
    batch, per_point_us, ts = 200, [], base
    for _ in range(60):
        ts_col = [ts + i for i in range(batch)]
        val_col = [50.0 + (i % 40) * 0.5 for i in range(batch)]
        ts += batch
        t0 = time.perf_counter()
        ring.record_series("cpu", ts_col, val_col)
        per_point_us.append((time.perf_counter() - t0) / batch * 1e6)
    shim = RingHistory()
    point_us, ts2 = [], base
    for _ in range(20):
        t0 = time.perf_counter()
        for i in range(batch):
            shim.record("cpu", 50.0 + (i % 40) * 0.5, ts=ts2)
            ts2 += 1.0
        point_us.append((time.perf_counter() - t0) / batch * 1e6)

    # Fleet-shaped ring: every /api/history series at 1 Hz for 30 min.
    fleet = RingHistory()
    names = list(PROM_QUERIES)
    for i in range(1800):
        for n in names:
            fleet.record(n, 30.0 + (i % 60) * 0.7, ts=base + i)
    svc = HistoryService(fleet, prometheus_url=None)
    q_ms = []
    for i in range(40):
        for n in names:  # the tick between queries
            fleet.record(n, 42.0, ts=base + 1800 + i)
        t0 = time.perf_counter()
        out = svc.snapshot_ring()
        q_ms.append((time.perf_counter() - t0) * 1e3)
    assert out["cpu"]["data"]

    # Resident bytes/point vs the pre-tentpole tuple-deque layout
    # holding the same stream (tuple header + two boxed floats + slot).
    col_bpp = fleet.resident_bytes() / max(1, fleet.count_points())
    dq = deque((base + i, 30.0 + (i % 60) * 0.7) for i in range(1800))
    dq_bytes = sys.getsizeof(dq) + sum(
        sys.getsizeof(p) + sys.getsizeof(p[0]) + sys.getsizeof(p[1]) for p in dq
    )
    deque_bpp = dq_bytes / len(dq)

    # Snapshot write/restore: v2 binary (chunks verbatim) with the v1
    # JSON writer alongside for the measured-speedup record.
    with tempfile.TemporaryDirectory() as td:
        bpath = os.path.join(td, "hist.bin")
        jpath = os.path.join(td, "hist.json")
        wr_ms, wr_json_ms, rd_ms = [], [], []
        for _ in range(10):
            t0 = time.perf_counter()
            assert HistorySnapshotter(fleet, bpath).save()
            wr_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            assert HistorySnapshotter(fleet, jpath, fmt="json").save()
            wr_json_ms.append((time.perf_counter() - t0) * 1e3)
            fresh = RingHistory()
            t0 = time.perf_counter()
            assert HistorySnapshotter(fresh, bpath).restore()
            rd_ms.append((time.perf_counter() - t0) * 1e3)
        snap_bytes = os.path.getsize(bpath)
        snap_json_bytes = os.path.getsize(jpath)

    # Per-chip recording at v5p-256: 256 chips × 4 metrics per tick,
    # through the sampler-shaped path — cached handles + ONE
    # record_batch per tick (the accum_many kernel call).
    pc = RingHistory()
    chip_ids = [f"host-{h}/chip-{c}" for h in range(64) for c in range(4)]
    handles = [
        (
            pc.handle(f"chip.{cid}.mxu"),
            pc.handle(f"chip.{cid}.hbm"),
            pc.handle(f"chip.{cid}.temp"),
            pc.handle(f"chip.{cid}.link"),
        )
        for cid in chip_ids
    ]
    pc_us = []
    for tick in range(30):
        tsx = base + tick
        pairs = []
        for hs in handles:
            pairs.append((hs[0], 50.0 + tick))
            pairs.append((hs[1], 60.0))
            pairs.append((hs[2], 40.5))
            pairs.append((hs[3], 0.0))
        t0 = time.perf_counter()
        pc.record_batch(pairs, ts=tsx)
        pc_us.append((time.perf_counter() - t0) / (len(chip_ids) * 4) * 1e6)

    return {
        "history_record_p50_us": round(_p50(per_point_us), 3),
        "history_record_point_p50_us": round(_p50(point_us), 3),
        "history_query_30m_p50_ms": round(_p50(q_ms), 3),
        "history_resident_bytes_per_point": round(col_bpp, 2),
        "history_deque_bytes_per_point": round(deque_bpp, 2),
        "history_bytes_vs_deque": round(deque_bpp / col_bpp, 2),
        "history_snapshot_write_ms": round(_p50(wr_ms), 3),
        "history_snapshot_json_write_ms": round(_p50(wr_json_ms), 3),
        "history_snapshot_bytes": snap_bytes,
        "history_snapshot_json_bytes": snap_json_bytes,
        "history_restore_ms": round(_p50(rd_ms), 3),
        "history_perchip_256_record_p50_us": round(_p50(pc_us), 3),
        "history_perchip_256_series": len(pc.series),
    }


def _bench_ingest_sync() -> dict:
    """Ingest spine (docs/perf.md): single-series batch append p50
    (µs/point, kernel vs forced-Python fallback) and the binary peer
    wire codec vs JSON at 256 chips (decode µs + encoded bytes)."""
    import json as _json

    from tpumon import tsdb
    from tpumon.collectors.accel_fake import FakeTpuCollector
    from tpumon.history import RingHistory
    from tpumon.protowire import decode_wire_frame, encode_wire_frame
    from tpumon.topology import chips_from_columns, chips_from_wire, chips_to_wire

    base = 1_700_000_000.0
    batch = 256

    def batch_us(iters: int = 60) -> float:
        ring = RingHistory()
        ts, out = base, []
        for _ in range(iters):
            ts_col = [ts + i for i in range(batch)]
            val_col = [50.0 + (i % 64) * 0.4 for i in range(batch)]
            ts += batch
            t0 = time.perf_counter()
            ring.record_series("mxu", ts_col, val_col)
            out.append((time.perf_counter() - t0) / batch * 1e6)
        return _p50(out)

    kern_us = batch_us()
    kernel_active = tsdb.kernel() is not None
    tsdb.set_kernel_enabled(False)
    try:
        py_us = batch_us()
    finally:
        tsdb.set_kernel_enabled(True)

    # Peer wire: binary frame vs JSON for a 256-chip snapshot — decode
    # to columns/payload, decode all the way to ChipSamples, and bytes.
    chips = FakeTpuCollector(topology="v5p-256").chips()
    w = chips_to_wire(chips)
    blob = encode_wire_frame(w["v"], w["fields"], w["rows"])
    jblob = _json.dumps(w).encode()

    def best_us(fn, iters: int = 30, rounds: int = 4) -> float:
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best * 1e6

    bin_us = best_us(lambda: decode_wire_frame(blob))
    json_us = best_us(lambda: _json.loads(jblob))
    bin_chips_us = best_us(
        lambda: chips_from_columns(*decode_wire_frame(blob)[1:])
    )
    json_chips_us = best_us(lambda: chips_from_wire(_json.loads(jblob)))
    assert chips_from_columns(*decode_wire_frame(blob)[1:]) == chips

    return {
        "ingest_batch_p50_us": round(kern_us, 3),
        "ingest_batch_py_p50_us": round(py_us, 3),
        "ingest_kernel_active": kernel_active,
        "wire_binary_decode_p50_us": round(bin_us, 1),
        "wire_json_decode_p50_us": round(json_us, 1),
        "wire_binary_chips_p50_us": round(bin_chips_us, 1),
        "wire_json_chips_p50_us": round(json_chips_us, 1),
        "wire_binary_bytes": len(blob),
        "wire_json_bytes": len(jblob),
    }


async def _bench_ingest_tick(iters: int = 40, warmup: int = 8) -> dict:
    """The tick-shaped ingest number: a live sampler on fake v5p-256
    with --history-per-chip 256 (1024 per-chip series + fleet series,
    one record_batch per tick). Reports the tick's history-stage p50
    (the ingest spine's share — what this phase exists to pin) plus the
    full tick p50 for context."""
    sampler, server, fetch = await _serve_bench_app(
        "fake:v5p-256", TPUMON_HISTORY_PER_CHIP="256"
    )
    try:
        for _ in range(warmup):
            await sampler.tick_fast()
        for _ in range(iters):
            await sampler.tick_fast()
        stages = sampler.tracer.to_json().get("stages", {})
        hist = stages.get("history", {})
        tick = stages.get("tick_fast", {})
    finally:
        await server.stop()
    return {
        "ingest_tick_256_p50_ms": hist.get("p50_ms"),
        "ingest_tick_256_full_p50_ms": tick.get("p50_ms"),
        "ingest_tick_256_series": len(sampler.history.series),
    }


async def _bench_federation(
    n_peers: int = 8, peer_topology: str = "v5e-8",
    key_prefix: str = "federation", iters: int = 40, warmup: int = 5,
) -> dict:
    """Monitor-at-scale: one aggregator federating n_peers in-process
    tpumon instances, each serving a fake host (default 8×v5e-8 —
    64 chips, a v5p-64-style fleet; the 256-chip variant federates
    4×v5p-64). Reports the merged scrape→render p50 through the
    aggregator's live HTTP server and the exporter render time at
    that chip count (VERDICT round-1 item #7)."""
    from tpumon.app import build
    from tpumon.collectors.accel_peers import PeerFederatedCollector
    from tpumon.config import load_config
    from tpumon.exporter import render_exporter

    peers = []
    try:
        urls = []
        for i in range(n_peers):
            cfg = load_config(
                env={
                    "TPUMON_PORT": "0",
                    "TPUMON_HOST": "127.0.0.1",
                    "TPUMON_ACCEL_BACKEND": f"fake:{peer_topology}@fleet{i}",
                    "TPUMON_K8S_MODE": "none",
                    "TPUMON_COLLECTORS": "accel",
                }
            )
            sampler, server = build(cfg)
            await sampler.tick_fast()
            await server.start()
            peers.append((sampler, server))
            urls.append(f"127.0.0.1:{server.port}")

        agg_cfg = load_config(
            env={
                "TPUMON_PORT": "0",
                "TPUMON_HOST": "127.0.0.1",
                "TPUMON_ACCEL_BACKEND": "none",
                "TPUMON_K8S_MODE": "none",
                "TPUMON_COLLECTORS": "accel",
                "TPUMON_PEERS": ",".join(urls),
            }
        )
        agg_sampler, agg_server = build(agg_cfg)
        assert isinstance(agg_sampler.accel, PeerFederatedCollector)
        await agg_sampler.tick_fast()
        await agg_server.start()
        peers.append((agg_sampler, agg_server))
        url = f"http://127.0.0.1:{agg_server.port}/api/accel/metrics"

        def fetch() -> dict:
            with urllib.request.urlopen(url) as r:
                return json.loads(r.read())

        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await agg_sampler.tick_fast()
            data = await asyncio.to_thread(fetch)
            dt = (time.perf_counter() - t0) * 1e3
            if i >= warmup:
                cycle_ms.append(dt)
        n_chips = len(data["chips"])

        render_ms: list[float] = []
        for _ in range(20):
            t0 = time.perf_counter()
            text = render_exporter(agg_sampler)
            render_ms.append((time.perf_counter() - t0) * 1e3)
        assert "tpu_mxu_duty_cycle_pct" in text
    finally:
        for sampler, server in peers:
            with contextlib.suppress(Exception):
                await server.stop()

    return {
        f"{key_prefix}_chips": n_chips,
        f"{key_prefix}_scrape_to_render_p50_ms": round(_p50(cycle_ms), 3),
        f"{key_prefix}_exporter_render_ms": round(_p50(render_ms), 3),
    }


async def _bench_federation_tree(
    n_leaves: int = 8, leaf_topology: str = "v5p-256", n_aggs: int = 2,
    iters: int = 30, warmup: int = 5,
) -> dict:
    """Pod-of-pods scale (ROADMAP item 2 / docs/federation.md): a fake
    v5p-2048 as 8×v5p-256 leaf monitors PUSHING delta frames to 2 slice
    aggregators, which push slice rollups to a fleet root — all real
    servers in-process. Numbers of record:

      federation_2048_root_scrape_p50_ms  root tick + GET /api/federation
                                          (the fleet view's scrape→render;
                                          acceptance: <= 2x the flat
                                          federation_256 number)
      federation_delta_bytes_per_tick     mean steady-state upstream bytes
                                          per leaf tick (acceptance: <= 25%
                                          of a binary keyframe)
      federation_resync_ms                forced uplink reconnect -> fresh
                                          keyframe landed at the aggregator
    """
    from tpumon.app import build
    from tpumon.config import load_config

    def mk(**env):
        base = {
            "TPUMON_PORT": "0", "TPUMON_HOST": "127.0.0.1",
            "TPUMON_K8S_MODE": "none", "TPUMON_COLLECTORS": "accel",
            "TPUMON_HISTORY_PER_CHIP": "0",
            "TPUMON_FEDERATION_DARK_AFTER_S": "30",
        }
        base.update(env)
        return build(load_config(env=base))

    nodes = []  # (sampler, server) for teardown
    try:
        root_s, root_srv = mk(
            TPUMON_ACCEL_BACKEND="none", TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="root",
        )
        await root_s.tick_fast()
        await root_srv.start()
        nodes.append((root_s, root_srv))
        aggs = []
        for a in range(n_aggs):
            agg_s, agg_srv = mk(
                TPUMON_ACCEL_BACKEND="none",
                TPUMON_FEDERATION_ROLE="aggregator",
                TPUMON_FEDERATION_NODE=f"agg{a}",
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{root_srv.port}",
            )
            await agg_s.tick_fast()
            await agg_srv.start()
            await agg_s.uplink.start()
            aggs.append(agg_s)
            nodes.append((agg_s, agg_srv))
        leaves = []
        for i in range(n_leaves):
            agg_port = nodes[1 + i * n_aggs // n_leaves][1].port
            leaf_s, leaf_srv = mk(
                TPUMON_ACCEL_BACKEND=f"fake:{leaf_topology}@leaf{i}",
                TPUMON_FEDERATION_NODE=f"leaf{i}",
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_port}",
            )
            await leaf_s.tick_fast()
            await leaf_s.uplink.start()
            leaves.append(leaf_s)
            nodes.append((leaf_s, leaf_srv))

        url = f"http://127.0.0.1:{root_srv.port}/api/federation"

        def fetch() -> dict:
            with urllib.request.urlopen(url) as r:
                return json.loads(r.read())

        async def settle():
            # Let uplink tasks wake on the tick event, push, and the
            # ingest tasks land the frames (same event loop).
            for _ in range(4):
                await asyncio.sleep(0.005)

        cycle_ms: list[float] = []
        data: dict = {}
        for i in range(warmup + iters):
            await asyncio.gather(*(lf.tick_fast() for lf in leaves))
            await settle()
            await asyncio.gather(*(ag.tick_fast() for ag in aggs))
            await settle()
            t0 = time.perf_counter()
            await root_s.tick_fast()
            data = await asyncio.to_thread(fetch)
            dt = (time.perf_counter() - t0) * 1e3
            if i >= warmup:
                cycle_ms.append(dt)
        n_chips = data["fleet"]["chips"]
        assert n_chips == n_leaves * 256, data["fleet"]
        assert data["fleet"]["dark_slices"] == 0

        # Steady-state wire cost, averaged over every leaf uplink.
        delta_bytes = [
            lf.uplink.enc.stats["delta_bytes"] / lf.uplink.enc.stats["delta_frames"]
            for lf in leaves
            if lf.uplink.enc.stats["delta_frames"]
        ]
        key_bytes = max(lf.uplink.enc.stats["keyframe_bytes"] for lf in leaves)
        mean_delta = sum(delta_bytes) / len(delta_bytes)

        # Resync: force-drop leaf0's uplink, measure until a fresh
        # keyframe from it lands at its aggregator.
        leaf0 = leaves[0]
        agg0 = aggs[0]
        ns = agg0.federation.nodes["leaf0"]
        keyframes0 = ns.keyframes
        t0 = time.perf_counter()
        leaf0.uplink.resync()
        while ns.keyframes == keyframes0:
            if time.perf_counter() - t0 > 30:
                raise RuntimeError("resync never completed")
            await leaf0.tick_fast()
            await asyncio.sleep(0.01)
        resync_ms = (time.perf_counter() - t0) * 1e3
    finally:
        for sampler, server in nodes:
            with contextlib.suppress(Exception):
                await sampler.stop()
            with contextlib.suppress(Exception):
                await server.stop()

    return {
        "federation_2048_root_scrape_p50_ms": round(_p50(cycle_ms), 3),
        "federation_2048_chips": n_chips,
        "federation_delta_bytes_per_tick": round(mean_delta, 1),
        "federation_keyframe_bytes": key_bytes,
        "federation_delta_vs_keyframe_pct": round(100 * mean_delta / key_bytes, 1),
        "federation_resync_ms": round(resync_ms, 1),
    }


async def _bench_federation_ha(leaf_topology: str = "v5p-256") -> dict:
    """Root HA failover (docs/federation.md "Root HA"): a dual-homed
    leaf pushing to an active+standby root pair, the active root killed
    mid-stream — all real servers in-process. Number of record:

      federation_failover_ms  kill the active root -> the standby holds
                              the leadership lease AND serves a fresh
                              fleet view (every leaf chip reporting)
                              from GET /api/federation. Silence
                              detection (2x the lease) dominates; the
                              data-plane rebuild is one keyframe resync.
    """
    from tpumon.app import build
    from tpumon.config import load_config

    lease_s = 0.5

    def mk(**env):
        base = {
            "TPUMON_PORT": "0", "TPUMON_HOST": "127.0.0.1",
            "TPUMON_K8S_MODE": "none", "TPUMON_COLLECTORS": "accel",
            "TPUMON_HISTORY_PER_CHIP": "0",
            "TPUMON_FEDERATION_DARK_AFTER_S": "30",
        }
        base.update(env)
        return build(load_config(env=base))

    nodes = []
    try:
        # Ports are dynamic (port 0), so each root's peer URL is
        # patched in after both servers have bound.
        placeholder = "http://127.0.0.1:9"
        root_a, srv_a = mk(
            TPUMON_ACCEL_BACKEND="none", TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="rootA",
            TPUMON_FEDERATION_PEER=placeholder,
            TPUMON_FEDERATION_LEASE_S=str(lease_s),
            TPUMON_FEDERATION_INITIAL_LEADER="1",
        )
        root_b, srv_b = mk(
            TPUMON_ACCEL_BACKEND="none", TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="rootB",
            TPUMON_FEDERATION_PEER=placeholder,
            TPUMON_FEDERATION_LEASE_S=str(lease_s),
        )
        for s, srv in ((root_a, srv_a), (root_b, srv_b)):
            await s.tick_fast()
            await srv.start()
            nodes.append((s, srv))
        root_a.leader.peer_url = f"http://127.0.0.1:{srv_b.port}"
        root_b.leader.peer_url = f"http://127.0.0.1:{srv_a.port}"
        await root_a.leader.start()
        await root_b.leader.start()
        t0 = time.perf_counter()
        while not root_a.leader.is_leader():
            if time.perf_counter() - t0 > 30:
                raise RuntimeError("bootstrap promotion never happened")
            await asyncio.sleep(0.01)

        leaf_s, leaf_srv = mk(
            TPUMON_ACCEL_BACKEND=f"fake:{leaf_topology}@leaf0",
            TPUMON_FEDERATION_NODE="leaf0",
            TPUMON_FEDERATE_UP=(
                f"http://127.0.0.1:{srv_a.port},"
                f"http://127.0.0.1:{srv_b.port}"
            ),
        )
        await leaf_s.tick_fast()
        await leaf_s.uplink.start()
        nodes.append((leaf_s, leaf_srv))
        n_chips = len(leaf_s.chips())

        def fetch(port: int) -> dict:
            url = f"http://127.0.0.1:{port}/api/federation"
            with urllib.request.urlopen(url) as r:
                return json.loads(r.read())

        async def fleet_chips(port: int) -> int:
            data = await asyncio.to_thread(fetch, port)
            fleet = data.get("fleet") or {}
            return fleet.get("chips") or 0

        # Steady state on the active root first.
        t0 = time.perf_counter()
        while await fleet_chips(srv_a.port) != n_chips:
            if time.perf_counter() - t0 > 30:
                raise RuntimeError("steady state never reached on rootA")
            await leaf_s.tick_fast()
            await asyncio.sleep(0.01)

        # HA steady state: the data plane converges in tens of ms, the
        # heartbeat only every lease_s/3 — wait until the standby has
        # observed the leader's generation, or the kill below measures
        # a bootstrap race instead of a real failover (and the standby
        # would promote from generation 0, not generation+1).
        t0 = time.perf_counter()
        while root_b.leader.generation < root_a.leader.generation:
            if time.perf_counter() - t0 > 30:
                raise RuntimeError("standby never observed the leader")
            await asyncio.sleep(0.01)

        # Kill the active root; the standby must detect the silence,
        # promote, and rebuild the fleet view from the rotated uplink's
        # keyframe.
        t_kill = time.perf_counter()
        await srv_a.stop()
        await root_a.stop()
        promote_ms = None
        while True:
            if promote_ms is None and root_b.leader.is_leader():
                promote_ms = (time.perf_counter() - t_kill) * 1e3
            if (
                root_b.leader.is_leader()
                and await fleet_chips(srv_b.port) == n_chips
            ):
                break
            if time.perf_counter() - t_kill > 60:
                raise RuntimeError("failover never completed")
            await leaf_s.tick_fast()
            await asyncio.sleep(0.01)
        failover_ms = (time.perf_counter() - t_kill) * 1e3
    finally:
        for sampler, server in nodes:
            with contextlib.suppress(Exception):
                await sampler.stop()
            with contextlib.suppress(Exception):
                await server.stop()

    return {
        "federation_failover_ms": round(failover_ms, 1),
        "federation_ha_promote_ms": round(promote_ms, 1),
        "federation_ha_generation": root_b.leader.generation,
        "federation_ha_lease_s": lease_s,
    }


async def _bench_trace_fed(
    n_leaves: int = 8, leaf_topology: str = "v5p-256", n_aggs: int = 2,
    iters: int = 15, warmup: int = 3,
) -> dict:
    """Fleet-tracing cost (ISSUE 19, docs/observability.md "Distributed
    tracing"): the 8-leaf federation tree of _bench_federation_tree,
    ticked A/B/A — tracing on, off, on again — so drift can't fake an
    overhead. Numbers of record:

      fed_freshness_p50_ms        leaf sample -> visible at the root,
                                  clock-offset corrected (the per-leaf
                                  fed.<node>.freshness_ms series the
                                  root records at ingest), tracing on
      trace_fed_overhead_tick_pct leaf tick p50 with span + TPWS + trace
                                  trailer shipping vs tracing off
                                  (acceptance: <= 1%)

    The off leg also proves the degradation contract structurally:
    every uplink must ship ZERO trace bytes (no TPWS records) and hold
    a None encoder trace context (no trailing context field) — tracing
    off adds nothing to the wire.
    """
    from tpumon.app import build
    from tpumon.config import load_config

    async def run(trace_ring: int) -> dict:
        def mk(**env):
            base = {
                "TPUMON_PORT": "0", "TPUMON_HOST": "127.0.0.1",
                "TPUMON_K8S_MODE": "none", "TPUMON_COLLECTORS": "accel",
                "TPUMON_HISTORY_PER_CHIP": "0",
                "TPUMON_FEDERATION_DARK_AFTER_S": "30",
                "TPUMON_TRACE_RING": str(trace_ring),
            }
            base.update(env)
            return build(load_config(env=base))

        nodes = []
        tick_ms: list[float] = []
        fresh_ms: list[float] = []
        try:
            root_s, root_srv = mk(
                TPUMON_ACCEL_BACKEND="none",
                TPUMON_FEDERATION_ROLE="root",
                TPUMON_FEDERATION_NODE="root",
            )
            await root_s.tick_fast()
            await root_srv.start()
            nodes.append((root_s, root_srv))
            aggs = []
            for a in range(n_aggs):
                agg_s, agg_srv = mk(
                    TPUMON_ACCEL_BACKEND="none",
                    TPUMON_FEDERATION_ROLE="aggregator",
                    TPUMON_FEDERATION_NODE=f"agg{a}",
                    TPUMON_FEDERATE_UP=f"http://127.0.0.1:{root_srv.port}",
                )
                await agg_s.tick_fast()
                await agg_srv.start()
                await agg_s.uplink.start()
                aggs.append(agg_s)
                nodes.append((agg_s, agg_srv))
            leaves = []
            for i in range(n_leaves):
                agg_port = nodes[1 + i * n_aggs // n_leaves][1].port
                leaf_s, leaf_srv = mk(
                    TPUMON_ACCEL_BACKEND=f"fake:{leaf_topology}@leaf{i}",
                    TPUMON_FEDERATION_NODE=f"leaf{i}",
                    TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_port}",
                )
                await leaf_s.tick_fast()
                await leaf_s.uplink.start()
                leaves.append(leaf_s)
                nodes.append((leaf_s, leaf_srv))

            async def settle():
                for _ in range(4):
                    await asyncio.sleep(0.005)

            for i in range(warmup + iters):
                t0 = time.perf_counter()
                await asyncio.gather(*(lf.tick_fast() for lf in leaves))
                dt = (time.perf_counter() - t0) * 1e3 / n_leaves
                await settle()
                await asyncio.gather(*(ag.tick_fast() for ag in aggs))
                await settle()
                await root_s.tick_fast()
                await settle()
                if i >= warmup:
                    tick_ms.append(dt)
                    for node, row in root_s.federation.freshness_now.items():
                        if node.startswith("leaf"):
                            fresh_ms.append(row["ms"])
            uplinks = [s.uplink for s, _ in nodes if s.uplink is not None]
            return {
                "tick_p50_ms": _p50(tick_ms),
                "fresh_ms": fresh_ms,
                "trace_bytes": sum(u.trace_bytes for u in uplinks),
                "spans_shipped": sum(u.spans_shipped for u in uplinks),
                "enc_traces": sum(
                    1 for u in uplinks if u.enc.trace is not None),
            }
        finally:
            for sampler, server in nodes:
                with contextlib.suppress(Exception):
                    await sampler.stop()
                with contextlib.suppress(Exception):
                    await server.stop()

    on_a = await run(4096)
    off = await run(0)
    on_b = await run(4096)
    if off["trace_bytes"] != 0 or off["enc_traces"] != 0:
        raise RuntimeError(
            f"tracing off leaked onto the wire: {off['trace_bytes']} TPWS "
            f"bytes, {off['enc_traces']} armed encoder contexts")
    if not (on_a["spans_shipped"] and on_b["spans_shipped"]):
        raise RuntimeError("tracing on shipped no spans — nothing measured")
    tick_on = min(on_a["tick_p50_ms"], on_b["tick_p50_ms"])
    overhead = 100.0 * (tick_on - off["tick_p50_ms"]) / off["tick_p50_ms"]
    fresh = on_a["fresh_ms"] + on_b["fresh_ms"]
    return {
        "fed_freshness_p50_ms": round(_p50(fresh), 3),
        "trace_fed_overhead_tick_pct": round(overhead, 2),
        "trace_fed_tick_on_p50_ms": round(tick_on, 3),
        "trace_fed_tick_off_p50_ms": round(off["tick_p50_ms"], 3),
        "trace_fed_spans_shipped": on_a["spans_shipped"],
        "trace_fed_trace_bytes": on_a["trace_bytes"],
        "trace_fed_off_trace_bytes": off["trace_bytes"],
    }


async def _bench_hetero(
    n_tpu: int = 8, n_gpu: int = 4, iters: int = 25, warmup: int = 5,
) -> dict:
    """Heterogeneous fleet (ISSUE 15, docs/federation.md "Mixed
    fleets"): 8 fake TPU leaves (v5p-64) + 4 fake GPU nodes
    (dgx-h100-8) pushing into one aggregator → root tree. Numbers of
    record:

      hetero_root_scrape_p50_ms     root tick + GET /api/federation on
                                    the MIXED fleet (acceptance: <= 1.1x
                                    the TPU-only number measured on the
                                    same tree before the GPU uplinks
                                    start — the GPU family must ride the
                                    accelerator-generic path, not a
                                    slow side channel)
      hetero_by_accel_query_p50_ms  distributed per-family ranking at
                                    the root — topk(3,
                                    avg_over_time(chip.mxu[5s])) by
                                    (accel) — partial aggregates only
    """
    from tpumon.app import build
    from tpumon.config import load_config

    def mk(**env):
        base = {
            "TPUMON_PORT": "0", "TPUMON_HOST": "127.0.0.1",
            "TPUMON_K8S_MODE": "none", "TPUMON_COLLECTORS": "accel",
            "TPUMON_HISTORY_PER_CHIP": "0",
            "TPUMON_FEDERATION_DARK_AFTER_S": "30",
        }
        base.update(env)
        return build(load_config(env=base))

    nodes = []
    try:
        root_s, root_srv = mk(
            TPUMON_ACCEL_BACKEND="none", TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="root",
        )
        await root_s.tick_fast()
        await root_srv.start()
        nodes.append((root_s, root_srv))
        agg_s, agg_srv = mk(
            TPUMON_ACCEL_BACKEND="none",
            TPUMON_FEDERATION_ROLE="aggregator",
            TPUMON_FEDERATION_NODE="agg0",
            TPUMON_FEDERATE_UP=f"http://127.0.0.1:{root_srv.port}",
        )
        await agg_s.tick_fast()
        await agg_srv.start()
        await agg_s.uplink.start()
        nodes.append((agg_s, agg_srv))

        def leaf(name, backend):
            # Leaves keep per-chip history ON (unlike the pure-scrape
            # tree bench): the by-(accel) fleet query reads chip.mxu
            # at the leaves.
            s, srv = mk(
                TPUMON_ACCEL_BACKEND=backend,
                TPUMON_FEDERATION_NODE=name,
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_srv.port}",
                TPUMON_HISTORY_PER_CHIP="256",
            )
            nodes.append((s, srv))
            return s

        tpu_leaves = [
            leaf(f"tpu{i}", f"fake:v5p-64@tpu{i}") for i in range(n_tpu)
        ]
        gpu_leaves = [
            leaf(f"gpu{i}", f"gpufake:dgx-h100-8@gpu{i}")
            for i in range(n_gpu)
        ]
        for lf in tpu_leaves + gpu_leaves:
            await lf.tick_fast()

        url = f"http://127.0.0.1:{root_srv.port}/api/federation"

        def fetch() -> dict:
            with urllib.request.urlopen(url) as r:
                return json.loads(r.read())

        async def settle():
            for _ in range(4):
                await asyncio.sleep(0.005)

        async def scrape_cycle(leaves) -> tuple[list[float], dict]:
            cycle_ms: list[float] = []
            data: dict = {}
            for i in range(warmup + iters):
                await asyncio.gather(*(lf.tick_fast() for lf in leaves))
                await settle()
                await agg_s.tick_fast()
                await settle()
                t0 = time.perf_counter()
                await root_s.tick_fast()
                data = await asyncio.to_thread(fetch)
                if i >= warmup:
                    cycle_ms.append((time.perf_counter() - t0) * 1e3)
            return cycle_ms, data

        # --- TPU-only baseline: the GPU uplinks haven't started, so
        # the tree is exactly the pre-ISSUE-15 shape. ---
        for lf in tpu_leaves:
            await lf.uplink.start()
        base_ms, data = await scrape_cycle(tpu_leaves)
        assert data["fleet"]["chips"] == n_tpu * 64, data["fleet"]

        # --- Mixed: the GPU nodes join the same tree. ---
        for lf in gpu_leaves:
            await lf.uplink.start()
        mixed_ms, data = await scrape_cycle(tpu_leaves + gpu_leaves)
        by_accel = data["fleet"]["by_accel"]
        assert by_accel.get("gpu", {}).get("chips") == n_gpu * 8, by_accel
        assert by_accel.get("tpu", {}).get("chips") == n_tpu * 64, by_accel

        # --- per-family fleet ranking, distributed (never raw points) --
        expr = "topk(3, avg_over_time(chip.mxu[5s])) by (accel)"
        q_ms: list[float] = []
        partitions: set[str] = set()
        for _ in range(15):
            await asyncio.gather(
                *(lf.tick_fast() for lf in tpu_leaves + gpu_leaves)
            )
            await settle()
            t0 = time.perf_counter()
            out = await root_s.federation.fleet_query(expr, timeout_s=10.0)
            q_ms.append((time.perf_counter() - t0) * 1e3)
            partitions = {
                r["labels"].get("accel") for r in out["result"]
            }
        assert partitions == {"tpu", "gpu"}, out
    finally:
        for sampler, server in nodes:
            with contextlib.suppress(Exception):
                await sampler.stop()
            with contextlib.suppress(Exception):
                await server.stop()

    base = _p50(base_ms)
    mixed = _p50(mixed_ms)
    return {
        "hetero_root_scrape_p50_ms": round(mixed, 3),
        "hetero_root_scrape_tpu_only_p50_ms": round(base, 3),
        "hetero_vs_tpu_only": round(mixed / base, 3) if base else None,
        "hetero_chips": n_tpu * 64 + n_gpu * 8,
        "hetero_by_accel_query_p50_ms": round(_p50(q_ms), 3),
    }


async def _bench_query() -> dict:
    """In-tree query engine (docs/query.md). Numbers of record:

      query_instant_p50_ms          topk(5, avg_over_time(chip.mxu[5m]))
                                    instant over a v5p-256-scale ring
                                    (1024 per-chip series, 10 min data)
      query_range_30m_p50_ms        avg(chip.mxu) on a 30 m / 30 s grid
                                    (query_history_walk_p50_ms — the raw
                                    /api/history render of the same ring
                                    — rides full results for comparison)
      query_rules_append_overhead_pct
                                    record_batch cost with recording
                                    rules registered vs without
                                    (acceptance: <= 2%)
      query_fed_2048_topk_p50_ms    distributed topk(5, rate(chip.hbm[1m]))
                                    over the fake v5p-2048 tree (8×v5p-256
                                    leaves -> 2 aggregators -> root),
                                    partial aggregates only — the
                                    TPWR bytes per query ride full results
    """
    from tpumon.history import HistoryService, RingHistory
    from tpumon.query import QueryEngine, RecordingRule, RuleSet

    # --- a v5p-256-scale ring: 256 chips × 4 series + fleet series ---
    n_chips, ticks = 256, 600
    now = time.time()

    def fill(ring: RingHistory) -> list:
        handles = []
        for c in range(n_chips):
            for metric in ("mxu", "hbm", "temp", "link"):
                handles.append(ring.handle(f"chip.h{c % 32}/c{c}.{metric}"))
        for name in ("cpu", "mxu", "hbm"):
            handles.append(ring.handle(name))
        for i in range(ticks):
            ts = now - ticks + i
            batch = [
                (h, 30.0 + (j * 7 + i) % 60) for j, h in enumerate(handles)
            ]
            ring.record_batch(batch, ts=ts)
        return handles

    ring = RingHistory()
    fill(ring)
    engine = QueryEngine(ring)

    expr = "topk(5, avg_over_time(chip.mxu[5m]))"
    instant_ms: list[float] = []
    for _ in range(40):
        t0 = time.perf_counter()
        out = engine.instant(expr, at=now)
        instant_ms.append((time.perf_counter() - t0) * 1e3)
    assert len(out["result"]) == 5

    range_ms: list[float] = []
    for _ in range(10):
        t0 = time.perf_counter()
        rq = engine.range_query("avg(chip.mxu)", 1800, 30, end=now)
        range_ms.append((time.perf_counter() - t0) * 1e3)
    assert rq["series"][0]["points"]

    svc = HistoryService(ring)
    walk_ms: list[float] = []
    for _ in range(10):
        ring._memo.clear()  # cold render, like a fresh window request
        t0 = time.perf_counter()
        svc.snapshot_ring(window_s=1800)
        walk_ms.append((time.perf_counter() - t0) * 1e3)

    # --- recording-rule append overhead ----------------------------------
    # The marginal work rules add to the append path is the batched
    # rule-store update (RuleSet.accum_batch — everything else in
    # record_batch is identical with or without rules), so measure IT
    # directly inside real ticks (cold caches, realistic batch) and
    # report it against the rule-free tick p50. A/B tick deltas are the
    # wrong instrument here: the effect is ~tens of µs on a ~2 ms tick,
    # below cross-run box noise.
    def mk_ring(with_rules: bool):
        r2 = RingHistory()
        if with_rules:
            r2.set_recording_rules(
                RuleSet([RecordingRule("chip.mxu[5m]"),
                         RecordingRule("chip.hbm[5m]")])
            )
        hs = []
        for c in range(n_chips):
            for metric in ("mxu", "hbm", "temp", "link"):
                hs.append(r2.handle(f"chip.h0/c{c}.{metric}"))
        return r2, hs

    def drive(ring2, hs2, accum_us: list[float] | None):
        if accum_us is not None:
            orig = RuleSet.accum_batch

            def timed(self, ts, val_q, slots):
                a0 = time.perf_counter()
                orig(self, ts, val_q, slots)
                accum_us.append((time.perf_counter() - a0) * 1e6)

            RuleSet.accum_batch = timed
        try:
            per: list[float] = []
            for i in range(400):
                vals = [40.0 + (j + i) % 50 for j in range(len(hs2))]
                batch = list(zip(hs2, vals))
                t0 = time.perf_counter()
                ring2.record_batch(batch, ts=now + i)
                if i >= 40:
                    per.append((time.perf_counter() - t0) * 1e3)
            return per
        finally:
            if accum_us is not None:
                RuleSet.accum_batch = orig

    ring_p, hs_p = mk_ring(False)
    t_plain = drive(ring_p, hs_p, None)
    ring_r, hs_r = mk_ring(True)
    accum_us: list[float] = []
    t_rules = drive(ring_r, hs_r, accum_us)
    accum_us = accum_us[40:]
    plain_p50 = _p50(t_plain)
    overhead_pct = 100.0 * (_p50(accum_us) / 1e3) / plain_p50
    measured = {"rules": _p50(t_rules), "plain": plain_p50}

    # --- distributed topk over the fake v5p-2048 tree --------------------
    from tpumon.app import build
    from tpumon.config import load_config

    def mk(**env):
        base = {
            "TPUMON_PORT": "0", "TPUMON_HOST": "127.0.0.1",
            "TPUMON_K8S_MODE": "none", "TPUMON_COLLECTORS": "accel",
            "TPUMON_FEDERATION_DARK_AFTER_S": "30",
        }
        base.update(env)
        return build(load_config(env=base))

    nodes = []
    fed_ms: list[float] = []
    query_bytes = 0
    try:
        root_s, root_srv = mk(
            TPUMON_ACCEL_BACKEND="none", TPUMON_FEDERATION_ROLE="root",
            TPUMON_FEDERATION_NODE="root", TPUMON_HISTORY_PER_CHIP="0",
        )
        await root_s.tick_fast()
        await root_srv.start()
        nodes.append((root_s, root_srv))
        aggs = []
        for a in range(2):
            agg_s, agg_srv = mk(
                TPUMON_ACCEL_BACKEND="none",
                TPUMON_FEDERATION_ROLE="aggregator",
                TPUMON_FEDERATION_NODE=f"agg{a}",
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{root_srv.port}",
                TPUMON_HISTORY_PER_CHIP="0",
            )
            await agg_s.tick_fast()
            await agg_srv.start()
            await agg_s.uplink.start()
            aggs.append(agg_s)
            nodes.append((agg_s, agg_srv))
        leaves = []
        for i in range(8):
            agg_port = nodes[1 + i // 4][1].port
            leaf_s, leaf_srv = mk(
                TPUMON_ACCEL_BACKEND=f"fake:v5p-256@leaf{i}",
                TPUMON_FEDERATION_NODE=f"leaf{i}",
                TPUMON_FEDERATE_UP=f"http://127.0.0.1:{agg_port}",
            )
            await leaf_s.tick_fast()
            await leaf_s.uplink.start()
            leaves.append(leaf_s)
            nodes.append((leaf_s, leaf_srv))
        # rate() needs >= 2 points per chip series; give every leaf a
        # few ticks and let the uplinks establish.
        for _ in range(3):
            await asyncio.gather(*(lf.tick_fast() for lf in leaves))
            await asyncio.sleep(0.02)
        deadline = time.monotonic() + 30
        while (
            sum(
                1
                for ag in aggs
                for ns in ag.federation.nodes.values()
                if ns.connected
            ) < 8
        ):
            if time.monotonic() > deadline:
                raise RuntimeError("leaves never connected")
            await asyncio.sleep(0.05)
        fed_expr = "topk(5, rate(chip.hbm[1m]))"
        answered0 = sum(lf.uplink.query_bytes for lf in leaves)
        for i in range(18):
            t0 = time.perf_counter()
            out = await root_s.federation.fleet_query(fed_expr, timeout_s=10)
            dt = (time.perf_counter() - t0) * 1e3
            if i >= 3:
                fed_ms.append(dt)
        assert len(out["result"]) == 5 and not out.get("partial"), out
        query_bytes = (
            sum(lf.uplink.query_bytes for lf in leaves) - answered0
        ) // 18
    finally:
        for sampler, server in nodes:
            with contextlib.suppress(Exception):
                await sampler.stop()
            with contextlib.suppress(Exception):
                await server.stop()

    return {
        "query_instant_p50_ms": round(_p50(instant_ms), 3),
        "query_range_30m_p50_ms": round(_p50(range_ms), 3),
        "query_history_walk_p50_ms": round(_p50(walk_ms), 3),
        "query_rules_append_overhead_pct": round(overhead_pct, 2),
        "query_rules_tick_ms": round(measured["rules"], 3),
        "query_plain_tick_ms": round(measured["plain"], 3),
        "query_fed_2048_topk_p50_ms": round(_p50(fed_ms), 3),
        "query_fed_bytes_per_query_per_leaf": query_bytes,
    }


async def _bench_slo(
    topology: str = "v5p-256", iters: int = 60, warmup: int = 5
) -> dict:
    """SLO engine overhead (docs/slo.md): live-sampler tick p50 with 8
    objectives (bad-condition eval + slo.bad append per tick; the
    burn/budget window aggregates ride recording rules on a short/24
    cadence) vs none — A/B interleaved min-of-rounds at the flagship
    256-chip shape, the two configs differing ONLY in TPUMON_SLOS.
    Acceptance ≤ 2%, the recording-rules bar."""
    slos = []
    for i in range(8):
        # Alternate never-bad and always-bad conditions over the live
        # fleet series so both the good and bad record paths, and the
        # window aggregates over each, are in the measurement.
        expr = "mxu > 1000" if i % 2 else "hbm >= 0"
        slos.append({
            "name": f"bench_{i}", "expr": expr, "target": 0.99,
            "window": "1h", "fast": ["5s", "30s"], "slow": ["15s", "60s"],
        })
    # Paired interleave, not the observability phase's separate-run A/B:
    # the effect under test (~0.3 ms of a ~16 ms tick) is below the
    # box-load drift between two multi-second bring-ups, so BOTH
    # samplers run in one process and alternate two-tick slices. The
    # overhead of record is p50(SLO stage) / p50(baseline tick) — the
    # stage is the ONLY on/off difference in the tick path, and a
    # direct stage measurement doesn't lose the ~0.3 ms signal in the
    # difference of two noisy multi-ms tick p50s (both operands stay
    # in full results for the cross-check).
    s_on, srv_on, _ = await _serve_bench_app(
        f"fake:{topology}", TPUMON_SLOS=json.dumps(slos))
    s_off, srv_off, _ = await _serve_bench_app(f"fake:{topology}")
    stage_ms: list[float] = []
    try:
        assert s_on.slo is not None and len(s_on.slo.compiled) == 8
        assert s_off.slo is None
        inner_observe = s_on.slo.observe

        def timed_observe(ts=None):
            t0 = time.perf_counter()
            changed = inner_observe(ts)
            stage_ms.append((time.perf_counter() - t0) * 1e3)
            return changed

        s_on.slo.observe = timed_observe
        for s in (s_on, s_off):
            for _ in range(warmup):
                await s.tick_fast()
        del stage_ms[:]
        on_ms: list[float] = []
        off_ms: list[float] = []
        for _round in range(iters):
            for s, acc in ((s_on, on_ms), (s_off, off_ms)):
                for _ in range(2):
                    t0 = time.perf_counter()
                    await s.tick_fast()
                    acc.append((time.perf_counter() - t0) * 1e3)
    finally:
        await srv_on.stop()
        await srv_off.stop()
    on, off, stage = _p50(on_ms), _p50(off_ms), _p50(stage_ms)
    out = {
        "slo_on_tick_p50_ms": round(on, 3),
        "slo_off_tick_p50_ms": round(off, 3),
        "slo_stage_p50_ms": round(stage, 3),
        "slo_eval_overhead_tick_pct": (
            round(100.0 * stage / off, 2) if off > 0 else None
        ),
    }
    out.update(_bench_traffic_sim())
    return out


def _bench_traffic_sim(total: int = 1000) -> dict:
    """Multi-tenant traffic-driver throughput: wall seconds to submit
    AND drain 1000 requests of the chat+rag+batch scenario mix through
    a small engine (tenant accounting on the hot path, rag behind a
    shared prefix). Backpressure-respecting: submissions pause while
    the queue is full, so nothing is rejected and every request's
    completion is part of the measurement."""
    from tpumon.loadgen.serving import ServingEngine
    from tpumon.loadgen.traffic import TenantSpec, TrafficSim

    engine = ServingEngine()
    tenants = [
        TenantSpec(name="chat", scenario="chat", max_new=8),
        TenantSpec(name="rag", scenario="rag", prompt_chunks=3, max_new=8),
        TenantSpec(name="batch", scenario="batch", max_new=16),
    ]
    sim = TrafficSim(engine, tenants, seed=7)
    # Warm the jits (prefill + decode) outside the timed window; its
    # completion predates t0, so it must not ride the reported counts.
    sim.fire("chat")
    while engine.step():
        pass
    warm = engine.completed_total
    order = ("chat", "chat", "rag", "batch")  # chat-heavy mix
    t0 = time.perf_counter()
    submitted = 0
    while submitted < total:
        with engine._lock:
            room = engine.max_queue - len(engine._queue)
        for _ in range(max(0, min(room, total - submitted))):
            sim.fire(order[submitted % len(order)])
            submitted += 1
        engine.step()
    while engine.step():
        pass
    wall_s = time.perf_counter() - t0
    completed = engine.completed_total - warm
    return {
        "traffic_sim_1k_requests_wall_s": round(wall_s, 3),
        "traffic_sim_requests_per_sec": round(completed / wall_s, 1),
        "traffic_sim_completed": completed,
    }


async def _bench_actuate(
    topology: str = "v5p-256", iters: int = 60, warmup: int = 5
) -> dict:
    """Actuation engine overhead (docs/actuation.md): live-sampler tick
    p50 with 8 policies (condition eval per tick — half steady-fired
    booleans, half recording-rule trend reads that ride the same
    append-time window store as the SLO engine) vs none. Same
    paired-interleave stage harness and rationale as the slo phase:
    both samplers run in one process, alternate two-tick slices, and
    the overhead of record is p50(actuate stage) / p50(baseline tick).
    Acceptance ≤ 1% of the v5p-256 tick. No actuator is bound, so the
    fired policies journal intent and drive nothing."""
    # Eight DISTINCT expressions (the engine memoizes condition
    # results by text, so duplicate conditions would measure ~2 evals
    # per tick, not 8): every policy pays its own evaluation; the four
    # trend conditions still share ONE recording-rule merge through
    # the eval-context (fn, series, window) memo, which is exactly the
    # production shape — distinct thresholds over a common trend.
    policies = []
    for i in range(8):
        if i % 2:
            when = f"avg_over_time(mxu[30s]) > {99990 + i}"
            action = {"action": "capacity", "prefill_budget": 2}
        else:
            # Always true: fires (dry) once, stays fired.
            when = f"hbm >= {-1 - i}"
            action = {"action": "shed"}
        policies.append({
            "name": f"bench_{i}", "when": when, "cooldown_s": 0,
            "fire_hold": 1, "clear_hold": 1, **action,
        })
    s_on, srv_on, _ = await _serve_bench_app(
        f"fake:{topology}", TPUMON_ACTUATIONS=json.dumps(policies))
    s_off, srv_off, _ = await _serve_bench_app(f"fake:{topology}")
    stage_ms: list[float] = []
    try:
        assert s_on.actuate is not None and len(s_on.actuate.policies) == 8
        assert s_off.actuate is None
        inner_observe = s_on.actuate.observe

        def timed_observe(ts=None):
            t0 = time.perf_counter()
            changed = inner_observe(ts)
            stage_ms.append((time.perf_counter() - t0) * 1e3)
            return changed

        s_on.actuate.observe = timed_observe
        for s in (s_on, s_off):
            for _ in range(warmup):
                await s.tick_fast()
        del stage_ms[:]
        on_ms: list[float] = []
        off_ms: list[float] = []
        for _round in range(iters):
            for s, acc in ((s_on, on_ms), (s_off, off_ms)):
                for _ in range(2):
                    t0 = time.perf_counter()
                    await s.tick_fast()
                    acc.append((time.perf_counter() - t0) * 1e3)
    finally:
        await srv_on.stop()
        await srv_off.stop()
    on, off, stage = _p50(on_ms), _p50(off_ms), _p50(stage_ms)
    out = {
        "actuate_on_tick_p50_ms": round(on, 3),
        "actuate_off_tick_p50_ms": round(off, 3),
        "actuate_stage_p50_ms": round(stage, 3),
        "actuate_eval_overhead_tick_pct": (
            round(100.0 * stage / off, 2) if off > 0 else None
        ),
    }
    out.update(_bench_actuate_recovery())
    return out


def _bench_actuate_recovery() -> dict:
    """Time-to-recover with vs without actuation: the soak's fault
    geometry run inline (no HTTP, no sampler). A bounded-queue engine
    under a chat-heavy mix takes a fixed-duration per-step stall;
    rejections inflate a windowed error-rate series the policy
    condition reads, and recovery is wall seconds from the page (first
    bad tick) until the error rate stays clean. Un-actuated, recovery
    structurally waits out the fault; actuated, the shed stops the
    rejections while the stall is still active."""
    from tpumon.actuate import (
        ActuationEngine,
        EngineActuator,
        parse_actuations,
    )
    from tpumon.events import EventJournal
    from tpumon.history import RingHistory
    from tpumon.loadgen.serving import ServingEngine
    from tpumon.loadgen.traffic import TenantSpec, TrafficSim
    from tpumon.query import QueryEngine

    # The accounting tick must span at least one stalled pump
    # iteration, or the zero-submission ticks between stall bursts
    # read as falsely clean (the soak hit the same aliasing on its
    # scrape interval — tests/test_actuate_soak.py).
    TICK_S = 0.3
    STALL_S = 0.25
    FAULT_S = 4.0
    THRESH = 0.05
    RATES = (("chat", 6.0), ("rag", 1.0), ("batch", 0.5))

    def run_arm(actuated: bool) -> float | None:
        engine = ServingEngine(max_queue=8)
        sim = TrafficSim(engine, [
            TenantSpec(name="chat", scenario="chat", rps=6.0, max_new=4),
            TenantSpec(name="rag", scenario="rag", rps=1.0,
                       prompt_chunks=3, max_new=4),
            TenantSpec(name="batch", scenario="batch", rps=0.5, max_new=8),
        ], seed=11)
        sim.fire("chat")  # jit warmup outside the judged window
        while engine.step():
            pass
        ring = RingHistory(window_s=600)
        specs, errs = parse_actuations([{
            "name": "shed", "when": f"err > {THRESH:g}", "action": "shed",
            "tenant": "*", "fraction": 0.8, "cooldown_s": 0,
            "fire_hold": 1, "clear_hold": 2,
        }])
        assert not errs, errs
        act = ActuationEngine(
            specs, QueryEngine(ring), ring, EventJournal(512),
            actuator=EngineActuator(engine) if actuated else None,
            shed_max_fraction=0.85)
        handle = ring.handle("err")
        acc = {name: 0.0 for name, _ in RATES}
        prev_rej = prev_sub = 0
        t0 = last = next_tick = time.perf_counter()
        fault_until = t0 + FAULT_S
        page_t = None
        clean = 0
        while time.perf_counter() - t0 < 25.0:
            now = time.perf_counter()
            for name, rate in RATES:
                acc[name] += rate * (now - last)
                while acc[name] >= 1.0:
                    acc[name] -= 1.0
                    sim.fire(name)
            last = now
            if not engine.step():
                time.sleep(0.002)
            if time.perf_counter() < fault_until:
                time.sleep(STALL_S)
            now = time.perf_counter()
            if now < next_tick:
                continue
            next_tick = now + TICK_S
            tot_rej = sum(t.rejected for t in engine.tenants.values())
            tot_sub = sum(t.submitted - t.shed
                          for t in engine.tenants.values())
            d_rej = tot_rej - prev_rej
            d_sub = tot_sub - prev_sub
            prev_rej, prev_sub = tot_rej, tot_sub
            err = d_rej / d_sub if d_sub > 0 else 0.0
            ring.record_batch([(handle, err)], ts=now)
            act.observe(now)
            if err > THRESH:
                if page_t is None:
                    page_t = now
                clean = 0
            elif page_t is not None and d_sub > 0:
                # Only ticks that actually observed submissions count
                # toward recovery: a zero-traffic tick proves nothing.
                clean += 1
                if clean >= 3:  # sustained clean: recovered
                    return now - page_t
        return None  # never paged or never recovered within the budget

    def safe_arm(label: str, actuated: bool):
        # One wedged arm nulls its own keys, not the whole phase.
        try:
            return run_arm(actuated)
        except Exception as e:
            _note(f"actuate recovery {label} failed: {e}")
            return None

    def best(vals):
        vals = [v for v in vals if v is not None]
        return min(vals) if vals else None

    # Alternating best-of-2 reps (the serving_concurrency pattern): the
    # wall-clock loop is sensitive to box load, and alternation keeps a
    # load burst from landing entirely on one arm.
    u1 = safe_arm("unactuated", False)
    a1 = safe_arm("actuated", True)
    u2 = safe_arm("unactuated", False)
    a2 = safe_arm("actuated", True)
    unact = best([u1, u2])
    actd = best([a1, a2])
    return {
        "actuate_time_to_recover_s": (
            round(actd, 2) if actd is not None else None),
        "actuate_time_to_recover_unactuated_s": (
            round(unact, 2) if unact is not None else None),
        "actuate_recovery_speedup": (
            round(unact / actd, 2) if unact and actd else None),
    }


def _note(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}", file=sys.stderr)


_T0 = time.perf_counter()

# Each phase runs in its own subprocess (device/compile state fully
# isolated; a wedged phase times out to nulls instead of hanging the
# driver). name -> (timeout_s, null-result keys).
PHASES: dict[str, tuple[float, tuple[str, ...]]] = {
    "scrape": (300, ("metric", "value", "unit", "vs_baseline")),
    "fastpath": (300, ("fastpath_64_scrape_to_render_p50_ms",
                       "exporter_render_64_ms",
                       "exporter_cached_render_64_ms",
                       "sse_keyframe_bytes_64", "sse_delta_bytes_64",
                       "fastpath_256_scrape_to_render_p50_ms",
                       "exporter_render_256_ms",
                       "exporter_cached_render_256_ms",
                       "sse_keyframe_bytes_256", "sse_delta_bytes_256")),
    "observability": (300, ("trace_on_tick_p50_ms", "trace_off_tick_p50_ms",
                            "trace_overhead_tick_pct",
                            "trace_on_scrape_to_render_p50_ms",
                            "trace_off_scrape_to_render_p50_ms",
                            "trace_overhead_scrape_pct",
                            "trace_spans_recorded")),
    "events": (300, ("events_append_p50_us",
                     "anomaly_on_tick_p50_ms", "anomaly_off_tick_p50_ms",
                     "anomaly_overhead_tick_pct")),
    "history": (300, ("history_record_p50_us", "history_query_30m_p50_ms",
                      "history_resident_bytes_per_point",
                      "history_deque_bytes_per_point",
                      "history_bytes_vs_deque",
                      "history_snapshot_write_ms",
                      "history_snapshot_json_write_ms",
                      "history_snapshot_bytes", "history_snapshot_json_bytes",
                      "history_restore_ms",
                      "history_perchip_256_record_p50_us",
                      "history_perchip_256_series")),
    "ingest": (300, ("ingest_batch_p50_us", "ingest_batch_py_p50_us",
                     "ingest_kernel_active",
                     "ingest_tick_256_p50_ms", "ingest_tick_256_full_p50_ms",
                     "ingest_tick_256_series",
                     "wire_binary_decode_p50_us", "wire_json_decode_p50_us",
                     "wire_binary_chips_p50_us", "wire_json_chips_p50_us",
                     "wire_binary_bytes", "wire_json_bytes")),
    "federation": (240, ("federation_chips",
                         "federation_scrape_to_render_p50_ms",
                         "federation_exporter_render_ms",
                         "federation_256_chips",
                         "federation_256_scrape_to_render_p50_ms",
                         "federation_256_exporter_render_ms")),
    "federation_tree": (300, ("federation_2048_root_scrape_p50_ms",
                              "federation_2048_chips",
                              "federation_delta_bytes_per_tick",
                              "federation_keyframe_bytes",
                              "federation_delta_vs_keyframe_pct",
                              "federation_resync_ms")),
    "federation_ha": (300, ("federation_failover_ms",
                            "federation_ha_promote_ms",
                            "federation_ha_generation",
                            "federation_ha_lease_s")),
    "trace_fed": (300, ("fed_freshness_p50_ms",
                        "trace_fed_overhead_tick_pct",
                        "trace_fed_tick_on_p50_ms",
                        "trace_fed_tick_off_p50_ms",
                        "trace_fed_spans_shipped",
                        "trace_fed_trace_bytes",
                        "trace_fed_off_trace_bytes")),
    "hetero": (300, ("hetero_root_scrape_p50_ms",
                     "hetero_root_scrape_tpu_only_p50_ms",
                     "hetero_vs_tpu_only",
                     "hetero_chips",
                     "hetero_by_accel_query_p50_ms")),
    "query": (300, ("query_instant_p50_ms", "query_range_30m_p50_ms",
                    "query_history_walk_p50_ms",
                    "query_rules_append_overhead_pct",
                    "query_rules_tick_ms", "query_plain_tick_ms",
                    "query_fed_2048_topk_p50_ms",
                    "query_fed_bytes_per_query_per_leaf")),
    "slo": (420, ("slo_on_tick_p50_ms", "slo_off_tick_p50_ms",
                  "slo_stage_p50_ms",
                  "slo_eval_overhead_tick_pct",
                  "traffic_sim_1k_requests_wall_s",
                  "traffic_sim_requests_per_sec",
                  "traffic_sim_completed")),
    "actuate": (420, ("actuate_on_tick_p50_ms", "actuate_off_tick_p50_ms",
                      "actuate_stage_p50_ms",
                      "actuate_eval_overhead_tick_pct",
                      "actuate_time_to_recover_s",
                      "actuate_time_to_recover_unactuated_s",
                      "actuate_recovery_speedup")),
    "kernels": (700, ("mxu_matmul_pallas_tflops", "mxu_matmul_xla_tflops",
                      "mxu_matmul_vs_xla",
                      "int8_matmul_pallas_tflops", "int8_matmul_xla_tflops",
                      "int8_matmul_vs_xla", "paged_attention_pallas_kv_gbps",
                      "paged_attention_xla_kv_gbps", "paged_attention_vs_xla",
                      "paged_engine_step_gather_ms",
                      "paged_engine_step_kernel_ms",
                      "paged_engine_step_kernel_vs_gather",
                      "kernel_marginal_s")),
    "train": (840, ("train_mfu_pct", "train_tokens_per_sec",
                    "train_mfu_naive_pct",
                    "train_seq8k_mfu_pct", "train_seq8k_tokens_per_sec",
                    "train_seq8k_chunked_mfu_pct")),
    "serving": (1500, ("serving_tokens_per_sec",
                      "serving_block8_tokens_per_sec",
                      "serving_spec_tokens_per_sec",
                      "serving_spec_accept_pct",
                      "serving_spec_draft_layers",
                      "serving_spec_draft_tokens_per_sec",
                      "serving_spec_draft_accept_pct",
                      "serving_copy_block8_tokens_per_sec",
                      "serving_spec_prompt_tokens_per_sec",
                      "serving_spec_prompt_accept_pct",
                      "serving_spec_prompt_vs_block8",
                      "serving_spec_prompt_workload",
                      "serving_paged_block8_tokens_per_sec",
                      "serving_paged_frag_block8_tokens_per_sec",
                      "serving_paged_kernel_block8_tokens_per_sec",
                      "serving_paged_kernel_vs_gather",
                      "serving_paged_spec_tokens_per_sec",
                      "serving_int8kv_block8_tokens_per_sec",
                      "serving_prefix_ttft_cold_ms",
                      "serving_prefix_ttft_hit_ms",
                      "serving_prefix_ttft_stats",
                      "serving_paged_prefix_ttft_cold_ms",
                      "serving_paged_prefix_ttft_hit_ms",
                      "serving_paged_prefix_ttft_stats",
                      "serving_requests")),
    "serving_concurrency": (600, (
        "serving_conc32_tokens_per_sec",
        "serving_conc128_tokens_per_sec",
        "serving_conc128_ttft_p95_ms",
        "serving_conc128_ttft_p95_sequential_ms",
        "serving_conc32_ttft_p95_ms",
        "serving_conc32_ttft_p95_sequential_ms",
        "serving_conc32_tokens_per_sec_sequential",
        "serving_conc128_tokens_per_sec_sequential",
        "serving_conc128_ttft_p95_speedup",
        "serving_conc128_tps_vs_sequential")),
    "serving_mesh": (600, (
        "serving_mesh_128_tokens_per_sec",
        "serving_single_128_tokens_per_sec",
        "serving_mesh_128_tps_vs_single",
        "serving_mesh_ttft_p95_ms",
        "serving_single_ttft_p95_ms",
        "serving_ring_max_context_tokens",
        "serving_ring_flat_max_context_tokens")),
}


# The headline scalar per phase family — the driver's number-of-record.
# Everything else (ratios' operands, IQR/oracle stats dicts, marginal
# durations) lives only in the full results file. Keep this list scalar
# and short: the serialized summary must stay under the driver's
# tail-capture budget (tests/test_bench_artifact.py pins < 1800 bytes).
KEYS_OF_RECORD: tuple[str, ...] = (
    # scrape (driver metric contract: metric/value/unit/vs_baseline)
    "metric", "value", "unit", "vs_baseline",
    "sampler_samples_per_sec", "accel_backend",
    # fastpath (256-chip cached render + delta SSE, docs/perf.md; the
    # 64-chip pair, cold exporter render and keyframe bytes live in
    # full results — the at-scale cached render and steady-state delta
    # are the numbers of record)
    "fastpath_256_scrape_to_render_p50_ms",
    "sse_delta_bytes_256",
    # observability (self-trace overhead at v5p-64,
    # docs/observability.md; the scrape-path overhead — the same story
    # measured at the render path, ~0.3% — lives in full results)
    "trace_overhead_tick_pct",
    # events (journal append p50, docs/events.md; the EWMA detector's
    # ~0% tick overhead lives in full results)
    "events_append_p50_us",
    # history engine (columnar store, docs/perf.md history section;
    # the vs-deque ratio, resident-bytes/point, json-write comparison
    # and the snapshot write/restore times live in the full results
    # file — the summary line's byte budget is pinned)
    "history_record_p50_us", "history_query_30m_p50_ms",
    # ingest spine (batch append + native kernel + binary peer wire,
    # docs/perf.md; the raw batch-append p50 joined the py-fallback,
    # bytes comparisons and wire decode p50 in full results — the
    # live-sampler ingest_tick_256_p50_ms is the same story measured
    # end-to-end, and the summary byte budget needed the room)
    "ingest_tick_256_p50_ms",
    # federation (flat peer fan-out + the push-based aggregator tree,
    # docs/federation.md; the 64-chip flat number, keyframe bytes, chip
    # counts and the delta-vs-keyframe ratio live in full results)
    "federation_256_scrape_to_render_p50_ms",
    "federation_2048_root_scrape_p50_ms",
    "federation_delta_bytes_per_tick",
    # federation_ha (root HA failover, docs/federation.md "Root HA";
    # the promote-only split, the final generation and the bench lease
    # length live in full results — as does federation_resync_ms, the
    # reconnect-only operand failover_ms subsumes, moved there to keep
    # the summary under its byte budget)
    "federation_failover_ms",
    # trace_fed (fleet tracing + freshness, docs/observability.md
    # "Distributed tracing"; the on/off tick operands, shipped-span and
    # TPWS byte counts live in full results)
    "fed_freshness_p50_ms",
    "trace_fed_overhead_tick_pct",
    # hetero (mixed TPU/GPU tree, docs/federation.md "Mixed fleets";
    # the TPU-only baseline operand, the ≤1.1x ratio, the chip count
    # and the by-accel query p50 live in full results — the query p50
    # moved there to keep the summary under its byte budget)
    "hetero_root_scrape_p50_ms",
    # query engine (in-tree PromQL subset, docs/query.md; the raw
    # history-walk comparison, the range-grid p50, per-config rule
    # tick operands and the per-leaf TPWR byte cost live in full
    # results — the instant p50 and the append-time-rules overhead
    # are the numbers of record)
    "query_instant_p50_ms",
    "query_rules_append_overhead_pct",
    "query_fed_2048_topk_p50_ms",
    # slo (burn-rate engine tick overhead + multi-tenant traffic-sim
    # throughput, docs/slo.md; the on/off tick operands and the
    # completed-request count live in full results)
    "slo_eval_overhead_tick_pct",
    "traffic_sim_1k_requests_wall_s",
    # actuate (policy-eval overhead as % of a v5p-256 tick + the
    # closed-loop recovery ratio, docs/actuation.md; the on/off/stage
    # tick operands and both time-to-recover operands live in full
    # results)
    "actuate_eval_overhead_tick_pct", "actuate_recovery_speedup",
    # kernels
    "mxu_matmul_pallas_tflops", "mxu_matmul_vs_xla",
    "int8_matmul_pallas_tflops", "int8_matmul_vs_xla",
    "paged_attention_pallas_kv_gbps", "paged_attention_vs_xla",
    # (the gather-path operand lives in full results next to the
    # kernel-vs-gather ratio — byte budget)
    "paged_engine_step_kernel_ms",
    # train
    "train_mfu_pct", "train_tokens_per_sec", "train_seq8k_mfu_pct",
    # serving (the int8-KV throughput, prompt-lookup ratio and prefix
    # TTFT pair moved to full results to make room for the concurrency
    # keys under the summary byte budget — prefix hit/cold remain as
    # diagnostics in BENCH_FULL.json)
    # (serving_spec_accept_pct and serving_spec_tokens_per_sec moved to
    # full results alongside the other spec diagnostics — byte budget;
    # the draft-model spec throughput was already there)
    "serving_tokens_per_sec", "serving_block8_tokens_per_sec",
    "serving_paged_block8_tokens_per_sec",
    "serving_paged_kernel_vs_gather",
    # serving_concurrency (chunked-prefill scheduler vs the sequential
    # stop-the-world baseline at 128-way concurrency; the conc32
    # numbers, the sequential-baseline operand, per-scheduler operands
    # and ratios live in full results)
    "serving_conc128_tokens_per_sec",
    "serving_conc128_ttft_p95_ms",
    # serving_mesh (dp×tp mesh engine vs the single-chip engine at a
    # fixed per-chip KV budget + the ring-attention admission ceiling,
    # docs/perf.md "Mesh serving"; both tokens/s operands, the single
    # TTFT operand and the flat ceiling live in full results)
    "serving_mesh_128_tps_vs_single",
    "serving_mesh_ttft_p95_ms",
    "serving_ring_max_context_tokens",
)

SUMMARY_MAX_BYTES = 1800


def compact_summary(result: dict, full_path: str) -> dict:
    """Keys-of-record only, nested dicts never — the one line the driver
    tail-captures. Missing keys serialize as null (a failed phase must
    still be visible in the record, not silently absent)."""

    def scalar(v):
        return None if isinstance(v, (dict, list)) else v

    out = {k: scalar(result.get(k)) for k in KEYS_OF_RECORD}
    out["full_results"] = full_path
    return out


def write_full_results(result: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")


def _run_phase(name: str, backend: str) -> dict:
    on_tpu = backend == "jax"
    if name == "scrape":
        return asyncio.run(_bench_scrape(backend))
    if name == "fastpath":
        async def both():
            out = await _bench_fastpath("v5p-64")
            out.update(await _bench_fastpath("v5p-256"))
            return out

        return asyncio.run(both())
    if name == "observability":
        return asyncio.run(_bench_observability())
    if name == "events":
        return asyncio.run(_bench_events())
    if name == "history":
        return _bench_history()
    if name == "ingest":
        async def both_ingest():
            out = _bench_ingest_sync()
            out.update(await _bench_ingest_tick())
            return out

        return asyncio.run(both_ingest())
    if name == "federation":
        async def both_scales():
            # 64 chips (8×v5e-8, the BENCH_r05-comparable shape) and
            # 256 chips (4×v5p-64) per round.
            out = await _bench_federation()
            out.update(await _bench_federation(
                n_peers=4, peer_topology="v5p-64",
                key_prefix="federation_256"))
            return out

        return asyncio.run(both_scales())
    if name == "federation_tree":
        return asyncio.run(_bench_federation_tree())
    if name == "federation_ha":
        return asyncio.run(_bench_federation_ha())
    if name == "trace_fed":
        return asyncio.run(_bench_trace_fed())
    if name == "hetero":
        return asyncio.run(_bench_hetero())
    if name == "query":
        return asyncio.run(_bench_query())
    if name == "slo":
        return asyncio.run(_bench_slo())
    if name == "actuate":
        return asyncio.run(_bench_actuate())
    if name == "kernels":
        if not on_tpu:
            # Keep the documented key set stable off-TPU: explicit nulls,
            # not silently-absent keys.
            return {k: None for k in PHASES["kernels"][1]}
        return _bench_kernels()
    if name == "train":
        return _bench_train(on_tpu)
    if name == "serving":
        return _bench_serving(on_tpu)
    if name == "serving_concurrency":
        return _bench_serving_concurrency(on_tpu)
    if name == "serving_mesh":
        return _bench_serving_mesh(on_tpu)
    raise ValueError(f"unknown phase {name!r}")


def main(argv: list[str] | None = None) -> int:
    import subprocess

    argv = sys.argv[1:] if argv is None else argv
    if "--phase" in argv:
        # Child mode: run one phase, print its JSON fragment.
        name = argv[argv.index("--phase") + 1]
        backend = argv[argv.index("--backend") + 1]
        if name == "serving_mesh":
            # The dp×tp mesh needs visible devices; on the CPU backend
            # that means forcing fake host devices BEFORE jax imports
            # (no phase imports jax at module scope, so this is early
            # enough in child mode).
            import os

            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        print(json.dumps(_run_phase(name, backend)))
        return 0

    out_path = "BENCH_FULL.json"
    if "--out" in argv:
        i = argv.index("--out")
        if i + 1 >= len(argv):
            print("bench.py: --out requires a path", file=sys.stderr)
            return 2
        out_path = argv[i + 1]
    backend = _detect_backend()
    _note(f"backend={backend}")
    result: dict = {}
    for name, (timeout_s, null_keys) in PHASES.items():
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--phase", name,
                 "--backend", backend],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr.strip()[-500:])
            result.update(json.loads(proc.stdout.strip().splitlines()[-1]))
            # Surface within-phase nulled-measurement reasons (the
            # child's safe() notes) — a null key whose cause is
            # invisible reads as mystery, not as the guard working.
            for line in proc.stderr.splitlines():
                if " failed: " in line:
                    _note(f"{name}: {line.strip()[:300]}")
            _note(f"{name} done")
        except Exception as e:
            _note(f"{name} FAILED: {type(e).__name__}: {str(e)[:200]}")
            for k in null_keys:
                result.setdefault(k, None)
    # Record-of-truth to disk, compact summary (< SUMMARY_MAX_BYTES, so
    # the driver's stdout tail always contains it whole) as the FINAL
    # stdout line. A failed file write must not take the summary with it.
    try:
        write_full_results(result, out_path)
        _note(f"full results -> {out_path}")
    except OSError as e:
        _note(f"full-results write FAILED: {e}")
    print(json.dumps(compact_summary(result, out_path), separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
