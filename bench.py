"""tpumon benchmark: per-chip scrape→render p50 latency + sampler rate.

Driver metric (BASELINE.json): "per-chip MXU%+HBM% scrape→render p50
latency; exporter samples/sec". One measured cycle is:

    trigger a fresh accel+host sample (sampler.tick_fast)
      → HTTP GET /api/accel/metrics against the live server
      → JSON parsed (the dashboard's render input)

i.e. the full data path a dashboard poll exercises, with collection
*included* (the reference collects synchronously inside the request —
execSync per hit, monitor_server.js:83-95 — so this is the comparable
unit of work).

vs_baseline: the reference publishes no latency numbers (BASELINE.md);
its effective scrape→render freshness is bounded by its 5 s realtime
polling interval (monitor.html:605, the reference's own headline
operational parameter). vs_baseline is therefore reported as
5000 ms / measured p50 — how many times fresher tpumon's pipeline is
than the reference's refresh cadence.

Runs against the real TPU backend when chips are visible, else the fake
v5e-8 topology (same pipeline, synthetic counters); an MXU burn runs
concurrently on the device so the measurement reflects a busy chip.
Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import asyncio
import json
import statistics
import sys
import threading
import time
import urllib.request


def _start_burn(stop: threading.Event) -> threading.Thread | None:
    """Background MXU load so scrape latency is measured under load."""

    def run():
        try:
            import jax

            from tpumon.loadgen.burn import mxu_burn

            size = 2048 if jax.devices()[0].platform == "tpu" else 128
            while not stop.is_set():
                mxu_burn(seconds=0.5, size=size, iters=8)
        except Exception:
            pass  # benching without load is still valid

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


async def _bench(iters: int = 50, warmup: int = 5) -> dict:
    from tpumon.app import build
    from tpumon.config import load_config

    # Prefer the real chip; fall back to the fake topology off-TPU. The
    # probe runs in a subprocess with a hard timeout because a wedged
    # device runtime hangs jax.devices() forever — bench must not hang
    # with it.
    backend = "fake:v5e-8"
    try:
        import subprocess

        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=90,
        )
        if probe.returncode == 0 and probe.stdout.strip() == "tpu":
            backend = "jax"
    except Exception:
        pass

    cfg = load_config(
        env={
            "TPUMON_PORT": "0",
            "TPUMON_HOST": "127.0.0.1",
            "TPUMON_ACCEL_BACKEND": backend,
            "TPUMON_K8S_MODE": "none",
            "TPUMON_COLLECTORS": "host,accel",
        }
    )
    sampler, server = build(cfg)
    await sampler.tick_all()
    await server.start()
    port = server.port
    url = f"http://127.0.0.1:{port}/api/accel/metrics"

    def fetch() -> dict:
        with urllib.request.urlopen(url) as r:
            return json.loads(r.read())

    stop = threading.Event()
    if backend == "jax":  # fake counters are synthetic; no point burning
        _start_burn(stop)
    try:
        cycle_ms: list[float] = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            await sampler.tick_fast()  # scrape: fresh device counters
            data = await asyncio.to_thread(fetch)  # render: HTTP + JSON
            dt = (time.perf_counter() - t0) * 1e3
            assert "chips" in data
            if i >= warmup:
                cycle_ms.append(dt)

        # Sampler-only rate (exporter samples/sec): how fast the device
        # counter loop can run, excluding HTTP.
        t0 = time.perf_counter()
        n = 50
        for _ in range(n):
            await sampler.tick_fast()
        samples_per_sec = n / (time.perf_counter() - t0)
    finally:
        stop.set()
        await server.stop()

    p50 = statistics.median(cycle_ms)
    p95 = sorted(cycle_ms)[int(0.95 * len(cycle_ms)) - 1]
    chips = len(sampler.chips())
    return {
        "metric": "accel_scrape_to_render_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(5000.0 / p50, 1),
        "p95_ms": round(p95, 3),
        "sampler_samples_per_sec": round(samples_per_sec, 1),
        "chips": chips,
        "accel_backend": backend,
    }


def main() -> int:
    result = asyncio.run(_bench())
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
